"""FleetAutoscaler: close the fleet-load → ``InferenceService.replicas`` loop.

The serving twin of `controller/autoscaler.ElasticAutoscaler` — a second
control loop over the ``InferenceService`` CRD that *decides* replica
counts from observed serving load, while the existing reconciler
(`controller/inferenceservice.py`) *executes* the resulting spec change
with its surge/drain machinery:

* per registered service (``spec.autoscale`` set), every tick: collect
  one ``FleetSample`` — from an attached in-process ``ServingFleet``
  (`autoscale/signals.FleetScraper` delta-reads its per-replica
  histograms) or by tailing replica pod logs for the extended
  ``[elastic-metrics]`` observation line the fleet prints
  (`serve/fleet.ServingFleet.observation_line`);
* fold the window into a ``FleetObservation``
  (`autoscale/signals.SignalAggregator` — dead scrapes mark the window
  stale, never zero);
* run the deterministic target-tracking policy
  (`autoscale/policy.Recommender`: SLO targets, utilization band,
  slice-legal steps, hysteresis, cooldowns, flap damping, warm floor);
* execute: patch ``spec.replicas`` through the cluster client (the
  reconciler and/or an attached fleet's ``scale_to`` do the rest), write
  the decision into ``status.desired_replicas`` / ``autoscale_message``,
  and append one stable line to ``decision_log`` — the byte-comparable
  artifact `make autoscale-soak` replays.

Failure discipline: a chaos/genuine scrape failure records a dead sample
(staleness holds last-known-good); a failed patch
(``SITE_AUTOSCALE_PATCH``) burns NO cooldown — ``Recommender.commit``
runs only after the write lands — so the loop retries at full speed next
tick instead of sulking through a cooldown it never used.

``run_once()`` is the deterministic unit tests and soak drive; ``run()``
wraps it in a thread at ``serving_autoscale_period_seconds`` cadence,
wired in `main.py` beside the elastic autoscaler.

Both the service loop and the per-pool loops ride the shared
observe→decide→commit kernel (`controller/loopkernel.LoopKernel`): the
kernel's ``run_tick`` template drives the hooks on ``_ServiceState`` /
``_PoolState`` and lands one decision-ledger record per decision
(`obs/ledger.py` — signals + trace exemplars, SLO-page/chaos triggers,
commit outcome, effect horizon), while the decision_log bytes stay
identical to the pre-kernel format (the soak byte-compares prove it).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from tpu_on_k8s import chaos
from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import Pod
from tpu_on_k8s.api.inference_types import (
    DecodePolicy,
    InferenceService,
    ModelStatus,
    SLOObjectiveStatus,
)
from tpu_on_k8s.autoscale.policy import (
    ACTION_DOWN,
    ACTION_UP,
    Recommender,
)
from tpu_on_k8s.autoscale.signals import (
    FleetObservation,
    FleetSample,
    FleetScraper,
    SignalAggregator,
    dead_sample,
    line_watermark,
    sample_from_line,
)
from tpu_on_k8s.client.cluster import InMemoryCluster, NotFoundError
from tpu_on_k8s.controller.config import JobControllerConfig
from tpu_on_k8s.controller.loopkernel import (
    LoopKernel,
    OpenHorizon,
    format_commit_failure_line,
)
from tpu_on_k8s.metrics.metrics import AutoscaleMetrics
from tpu_on_k8s.obs.ledger import (
    COMMIT_LANDED,
    HORIZON_BURN_RECOVERED,
    HORIZON_REPLICAS_READY,
    HORIZON_ROLLOUT_COMPLETE,
    committed,
)
from tpu_on_k8s.obs.slo import SLOEngine, SLOSpec, page_onsets
from tpu_on_k8s.obs.trace import ensure as ensure_tracer
from tpu_on_k8s.utils.logging import get_logger

_log = get_logger("fleetautoscaler")


def _fmt_signal(v: Optional[float]) -> str:
    return "none" if v is None else f"{v:.6f}"


@dataclasses.dataclass(frozen=True)
class _TickPack:
    """Everything one loop tick observed — the value the kernel's
    ``observe`` hook hands to ``decide``/``commit`` and the provenance
    hooks (`controller/loopkernel.LoopKernel`)."""

    sample: FleetSample
    obs: FleetObservation
    cur: int
    now: float
    urgent: bool = False


class _AutoscaleLoop(LoopKernel):
    """The shared anatomy of the service and per-pool decision loops,
    riding the observe→decide→commit kernel: the recommender owns the
    policy + tempo gate, the aggregator owns the signal window, the
    scraper owns delta-read positions, and the kernel owns the tick
    counter, ledger emission, and the effect horizon."""

    #: the owning controller, TYPED (set by its tick before run_tick):
    #: the concurrency analyzer's call graph follows hook→controller
    #: edges through this attribute — an untyped ctx-dict hop would
    #: sever the autoscaler thread root from its cluster-mutation paths
    owner: Optional["FleetAutoscaler"] = None

    def __init__(self) -> None:
        super().__init__()
        self.recommender: Optional[Recommender] = None
        self.policy_key: Optional[Tuple] = None
        self.aggregator: Optional[SignalAggregator] = None
        self.scraper = FleetScraper()
        #: chaos event seq drawn THIS tick's collect (0 = none) — the
        #: ledger's ``chaos#N`` trigger join key
        self.tick_chaos_seq = 0

    def bind_owner(self, owner: "FleetAutoscaler") -> None:
        self.owner = owner

    # ------------------------------------------------------------ kernel hooks
    def decide(self, pack: _TickPack, ctx):
        decision = self.recommender.decide(pack.obs, pack.cur, pack.now,
                                           urgent=pack.urgent)
        ctx["span"].set(action=decision.action, current=pack.cur,
                        target=decision.target, stale=pack.obs.stale,
                        queue_depth=pack.obs.queue_depth)
        return decision

    def record(self, pack: _TickPack, decision, ctx) -> None:
        self.owner._record(ctx["key"], ctx["svc"], pack.obs, decision,
                           pool=ctx.get("pool"))

    def commit(self, pack: _TickPack, decision, ctx) -> str:
        return self.owner._execute(ctx["key"], ctx["svc"], ctx["state"],
                                   self.recommender, decision, pack.now,
                                   pool=ctx.get("pool"))

    # -------------------------------------------------------- provenance hooks
    def tick_of(self, pack: _TickPack) -> int:
        return pack.obs.seq

    def signals_of(self, pack: _TickPack) -> Tuple[Tuple[str, str], ...]:
        o = pack.obs
        return (("ttft_p95", _fmt_signal(o.ttft_p95)),
                ("queue_wait_p95", _fmt_signal(o.queue_wait_p95)),
                ("tpot_p95", _fmt_signal(o.tpot_p95)),
                ("swap_p95", _fmt_signal(o.swap_p95)),
                ("queue_depth", str(o.queue_depth)),
                ("inflight", str(o.inflight_tokens)),
                ("slots", str(o.slots)),
                ("ready", str(o.ready_replicas)),
                ("stale", str(int(o.stale))))

    def exemplars_of(self, pack: _TickPack) -> Tuple[int, ...]:
        return pack.sample.exemplars

    def trigger_of(self, pack: _TickPack, ctx) -> str:
        if self.tick_chaos_seq:
            return f"chaos#{self.tick_chaos_seq}"
        return ""

    def horizon_events(self, h: OpenHorizon, pack: _TickPack, ctx):
        """The observable effect ends: a committed scale-up's replicas
        going ready, a committed scale-down's drain completing. A stale
        window proves nothing either way."""
        obs = pack.obs
        out = []
        if obs.stale:
            return out
        if h.action == ACTION_UP and obs.ready_replicas >= h.target:
            out.append((HORIZON_REPLICAS_READY, True))
        elif h.action == ACTION_DOWN and obs.ready_replicas <= h.target:
            out.append((HORIZON_ROLLOUT_COMPLETE, True))
        return out


class _PoolState(_AutoscaleLoop):
    """One pool's decision loop (disaggregated services run two of
    these — prefill and decode — instead of one service-level loop).
    The scraper is per pool: the pools' replicas are disjoint, and a
    shared scraper would interleave their sequence numbers."""

    def observe(self, ctx) -> Optional[_TickPack]:
        a, key, state = self.owner, ctx["key"], ctx["state"]
        sample = a._collect_pool(key, state, ctx["pool"], self)
        a._feed_slo(state, sample)
        now = a.clock()
        obs = self.aggregator.record(sample, now=now)
        cur = max(int(ctx["pspec"].replicas), 1)
        return _TickPack(sample=sample, obs=obs, cur=cur, now=now)


class _ServiceState(_AutoscaleLoop):
    """Per-service loop state: the policy's tempo gate lives in the
    recommender; the aggregator owns the signal window; ``fleet`` is the
    optional in-process execution target (single-binary serving)."""

    def __init__(self) -> None:
        super().__init__()
        self.fleet = None
        self.apply_to_fleet = True
        #: per-pool loops (``spec.pools.<pool>.autoscale`` present)
        self.pools: Dict[str, _PoolState] = {}
        #: newest observation-line batch consumed, PER POD — every pod's
        #: fleet runs its own step counter, so one shared watermark would
        #: permanently blind the scrape to any pod that started later
        self.watermark: Dict[str, int] = {}
        # --- SLO evaluation (``spec.slo`` present; `obs/slo.py`) ---
        self.slo_engine: Optional[SLOEngine] = None
        self.slo_key: Optional[Tuple] = None
        #: one cooldown bypass per page episode: set when a paging
        #: objective's urgency executed a scale-up, cleared when no
        #: objective pages — "bypass the up-cooldown ONCE", dead-banded
        #: by the budget-state hysteresis
        self.slo_bypass_used = False
        #: last rendered status.slo (avoids a status write per tick)
        self.slo_written: Optional[Dict] = None
        #: whether any non-stale objective currently pages, whether the
        #: last evaluation had a LIVE (non-stale) objective at all, and
        #: the 1-based page-episode ordinal (the count of page-onset
        #: transition lines in the budget log — by construction the
        #: ledger's ``slo_page:<svc>#N`` trigger resolves to a real
        #: line, even when paging resumes after a stale gap)
        self.slo_paging = False
        self.slo_live = False
        self.page_episode = 0
        #: ledger seq of the committed scale-UP that answered the
        #: current page episode — the decision the episode's
        #: ``burn_recovered`` event will reference, whether or not its
        #: effect horizon is still open (the capacity loop typically
        #: moves on — scales down, re-scales — before the backward-
        #: looking budget window formally refills; recovery belongs to
        #: the EPISODE, not to one horizon surviving long enough)
        self.page_up_seq: Optional[int] = None
        # --- per-model SLO evaluation (``spec.models[].slo``) ---
        #: model name → its own SLOEngine, fed through the autoscaler's
        #: ``observe_model_latency`` and published to
        #: ``status.models[name].slo`` — a model can burn its budget
        #: while the service-level aggregate looks healthy (zipf
        #: traffic: the head models drown the tail in every aggregate)
        self.model_slo: Dict[str, SLOEngine] = {}
        self.model_slo_key: Optional[Tuple] = None
        self.model_slo_written: Optional[Dict] = None

    # ------------------------------------------------------------ kernel hooks
    def observe(self, ctx) -> Optional[_TickPack]:
        a, svc, key = self.owner, ctx["svc"], ctx["key"]
        sample = a._collect(key, svc, self)
        now = a.clock()
        obs = self.aggregator.record(sample, now=now)
        cur = max(int(svc.spec.replicas), 0)
        # SLO evaluation rides the same tick: feed the fresh scrape,
        # evaluate burn rates, publish status.slo, and derive the
        # severity hint. ``spec.slo`` absent → all of this is a no-op
        # and the decision path is byte-identical.
        urgent = a._tick_slo(key, svc, self, sample, ctx["span"])
        return _TickPack(sample=sample, obs=obs, cur=cur, now=now,
                         urgent=urgent)

    def commit(self, pack: _TickPack, decision, ctx) -> str:
        outcome = super().commit(pack, decision, ctx)
        if committed(outcome) and pack.urgent \
                and decision.action == ACTION_UP \
                and decision.reason.startswith("slo_page"):
            # the bypass is spent only when it actually pierced a
            # cooldown (the policy marks those ``slo_page``) AND the
            # commit landed — a patch the chaos layer or the capacity
            # broker refused never scaled anything, so the episode
            # keeps its one escape hatch and retries at full urgency
            # next tick (the cooldown twin of the failed-patch
            # no-burn rule); it re-arms after the page episode clears
            self.slo_bypass_used = True
        return outcome

    def trigger_of(self, pack: _TickPack, ctx) -> str:
        decision = ctx.get("decision")
        if self.slo_paging and (decision is None
                                or decision.action != ACTION_DOWN):
            # downs during a lingering page are signal-driven (the
            # queue drained; the backward-looking budget just hasn't
            # refilled yet) — attributing them to the page would make
            # why_report claim the page CAUSED a scale-down
            return f"slo_page:{ctx['key']}#{self.page_episode}"
        return super().trigger_of(pack, ctx)

    def on_committed(self, rec, decision, outcome: str, ctx) -> None:
        if decision.action == ACTION_UP and self.slo_paging:
            # the decision that answered the page: the episode's
            # burn_recovered event will reference it (latest wins — the
            # last urgent escalation is the one that held)
            self.page_up_seq = rec.seq

    def horizon_events(self, h: OpenHorizon, pack: _TickPack, ctx):
        """On top of the shared ready/drain ends: an SLO-paged scale-up
        notes ``replicas_ready`` as PROGRESS — the
        page→decision→patch→recovery chain `tools/why_report.py`
        renders ends at the burn recovery, which the SLO tick emits as
        an episode-level event (see ``_evaluate_slo``) so it lands even
        when a later commit superseded this horizon first."""
        out = []
        slo_paged = h.trigger.startswith("slo_page")
        for event, closing in super().horizon_events(h, pack, ctx):
            if slo_paged and event == HORIZON_REPLICAS_READY:
                closing = False
            out.append((event, closing))
        return out


class FleetAutoscaler:
    """See module doc. One instance watches every autoscaled
    ``InferenceService`` in the cluster."""

    def __init__(self, cluster: InMemoryCluster,
                 config: Optional[JobControllerConfig] = None,
                 metrics: Optional[AutoscaleMetrics] = None,
                 clock: Callable[[], float] = time.monotonic,
                 tracer=None, slo_metrics=None, ledger=None,
                 broker=None) -> None:
        self.cluster = cluster
        self.config = config or JobControllerConfig()
        self.metrics = metrics
        # the capacity broker (`coordinator/broker.CapacityBroker`):
        # set, every scale-UP asks for chips BEFORE the spec patch —
        # a refusal returns ``conflict:BrokerRefused`` from the same
        # pre-patch position as a chaos fault, so no cooldown is ever
        # burned on capacity the market would not grant. Each
        # registered service also becomes a bidder (``serve/<key>``):
        # its standing bid is what the broker's ladder degrades
        # (DecodePolicy valves) or — for lower-priority services —
        # harvests. None → market-free operation, byte-identical.
        self.broker = broker
        # the decision ledger (`obs/ledger.DecisionLedger`): every
        # service/pool loop tick lands one provenance record through the
        # loop kernel. None → NOOP (bit-for-bit the ledger-free
        # behavior — decision logs and soak byte-compares see nothing).
        self.ledger = ledger
        # the SLO telemetry plane (`metrics.SLOMetrics`): burn-rate /
        # budget gauges + transition counters for every service whose
        # spec carries an ``slo`` block. None → mirror-free evaluation
        # (status.slo still gets written).
        self.slo_metrics = slo_metrics
        self.clock = clock
        # span producer (`tpu_on_k8s/obs/trace.py`): one
        # ``autoscale.tick`` span per (service|pool) decision, carrying
        # the observed signal and the action — the control-plane rows of
        # the same timeline the per-request spans populate. None → NOOP
        # (the decision_log byte-compare sees zero difference).
        self._tracer = ensure_tracer(tracer)
        #: stable one-line-per-decision record (byte-identical across two
        #: runs of the same seeded trace — the autoscale-soak contract).
        #: Bounded: one line per service per tick accrues forever on a
        #: long-lived operator, and a soak fits well inside the cap.
        self.decision_log: Deque[str] = deque(maxlen=10_000)
        self._lock = threading.Lock()
        self._services: Dict[str, _ServiceState] = {}
        # the bid price board for ``spec.broker.priced`` services:
        # ``key -> {"burn": ..., "queue": ...}``, written by the tick
        # thread (burn from `_evaluate_slo`, queue-per-slot from
        # `_record`), read by `_serving_bid` on the BROKER's tick
        # thread. Guarded by its own LEAF lock — always acquired alone,
        # so the bid path still never touches this autoscaler's `_lock`
        # (no lock-order edge between the two control loops)
        self._price_lock = threading.Lock()
        self._bid_prices: Dict[str, Dict[str, float]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ registration
    @staticmethod
    def _autoscaled(svc: InferenceService) -> bool:
        """A service participates when its service-level autoscale block
        is set, when it declares SLOs (``spec.slo`` — the tick is what
        evaluates them and writes ``status.slo``, scaling or not), or —
        disaggregated — when either pool carries an autoscale block."""
        if svc.spec.autoscale is not None or svc.spec.slo is not None:
            return True
        if any(m.slo is not None for m in svc.spec.models):
            return True   # per-model SLOs: the tick evaluates them too
        pools = svc.spec.pools
        return pools is not None and (
            pools.prefill.autoscale is not None
            or pools.decode.autoscale is not None)

    def register(self, svc: InferenceService) -> None:
        if not self._autoscaled(svc):
            return
        key = f"{svc.metadata.namespace}/{svc.metadata.name}"
        with self._lock:
            self._services.setdefault(key, _ServiceState())
        self._broker_register(key)

    def deregister(self, svc: InferenceService) -> None:
        key = f"{svc.metadata.namespace}/{svc.metadata.name}"
        with self._lock:
            state = self._services.pop(key, None)
        if state is not None:
            self._abandon_loops(state)
            self._broker_deregister(key)

    @staticmethod
    def _abandon_loops(state: "_ServiceState") -> None:
        """A retired service's loops close their open effect horizons
        (service AND pools) — a deleted-mid-scale service must not pin
        the shared ledger's open_effect_horizons gauge forever."""
        state.abandon()
        for ps in state.pools.values():
            ps.abandon()

    def observe_event(self, event) -> None:
        """Watch glue: register on ADDED/MODIFIED (the autoscale block
        may be added to an existing service), deregister on DELETED."""
        if event.kind != constants.KIND_INFERENCESERVICE:
            return
        if event.type in ("ADDED", "MODIFIED"):
            self.register(event.obj)
        elif event.type == "DELETED":
            self.deregister(event.obj)

    def registered(self) -> List[str]:
        with self._lock:
            return sorted(self._services)

    def attach_fleet(self, namespace: str, name: str, fleet, *,
                     apply: bool = True) -> None:
        """Bind an in-process ``ServingFleet`` as both the signal source
        (scraped directly, no log round-trip) and — with ``apply`` — the
        execution target (``fleet.scale_to`` after each committed
        patch). Single-binary deployments and the deterministic
        end-to-end tests use this; the CRD-only path tails pod logs."""
        key = f"{namespace}/{name}"
        with self._lock:
            state = self._services.setdefault(key, _ServiceState())
            state.fleet = fleet
            state.apply_to_fleet = apply
        self._broker_register(key)

    def _fleet_binding(self, state: _ServiceState):
        """Snapshot ``(fleet, apply_to_fleet)`` under the lock — the
        tick thread reads them while ``attach_fleet`` (main/watch
        thread) rebinds them; a torn read could scrape fleet A and
        apply the decision to fleet B."""
        with self._lock:
            return state.fleet, state.apply_to_fleet

    # --------------------------------------------------------- capacity market
    def _broker_register(self, key: str) -> None:
        """Make the service a bidder on the capacity market (idempotent
        — re-registering would reset the lane's ledger loop). The
        bid/apply/degrade closures run on the BROKER's tick thread and
        touch only the cluster client (its own lock) and the
        ``_price_lock`` leaf — never this autoscaler's lock, so no
        lock-order edge exists between the two control loops."""
        broker = self.broker
        if broker is None:
            return
        name = f"serve/{key}"
        if name in broker.consumers():
            return
        broker.register(
            name,
            lambda: self._serving_bid(key),
            apply_fn=lambda target, reason: self._broker_apply(
                key, target, reason),
            degrade_fn=lambda apply: self._broker_degrade(key, apply))

    def _broker_deregister(self, key: str) -> None:
        if self.broker is not None:
            self.broker.deregister(f"serve/{key}")
        with self._price_lock:
            self._bid_prices.pop(key, None)

    def _serving_bid(self, key: str):
        """The service's standing bid: hold what the spec holds (it
        expresses no future want — growth arrives through the
        ``request_capacity`` gate in ``_execute``), floored at the
        autoscale minimum plus the warm floor so a harvest can never
        cut below what ``warm_floor`` scale-downs already protect.

        With ``spec.broker.priced``, ``marginal_utility`` is the live
        price off the board: SLO fast-burn rate plus queue depth per
        slot, as of this autoscaler's last tick. The broker's victim
        sort already orders equal-priority victims by ascending
        utility, so a burning service keeps its chips while an idle
        equal-priority one is harvested first. Unpriced bids keep the
        static 0.0 — broker decisions for all-static configs are
        byte-identical with or without this feature."""
        from tpu_on_k8s.coordinator.broker import (
            KIND_SERVING, PRIORITY_SERVING, Bid)
        ns, svc_name = key.split("/", 1)
        svc = self.cluster.try_get(InferenceService, ns, svc_name)
        if svc is None:
            return None
        if svc.spec.pools is not None:
            sp = svc.spec.pools.normalized()
            cur = max(int(sp.prefill.replicas), 0) \
                + max(int(sp.decode.replicas), 0)
            floors = [max(p.autoscale.min_replicas, p.autoscale.min_warm)
                      for p in (sp.prefill, sp.decode)
                      if p.autoscale is not None]
            floor = sum(floors) if floors else cur
        else:
            cur = max(int(svc.spec.replicas), 0)
            ap = svc.spec.autoscale
            floor = (max(ap.min_replicas, ap.min_warm)
                     if ap is not None else cur)
        bp = svc.spec.broker
        utility = 0.0
        if bp is not None and bp.priced:
            with self._price_lock:
                price = dict(self._bid_prices.get(key) or ())
            utility = round(price.get("burn", 0.0)
                            + price.get("queue", 0.0), 6)
        return Bid(
            name=f"serve/{key}", kind=KIND_SERVING,
            priority=bp.priority if bp is not None else PRIORITY_SERVING,
            current=cur, desired=cur, floor=min(floor, cur) if cur else 0,
            unit=bp.unit_chips if bp is not None else 1,
            marginal_utility=utility,
            preemption_cost=(bp.preemption_cost if bp is not None
                             else float(cur)))

    def _broker_apply(self, key: str, target_units: int,
                      reason: str) -> bool:
        """Execute a broker-pushed harvest: patch ``spec.replicas``
        down and let the reconciler's drain machinery do the rest. The
        broker never pushes below the bid's floor, and only ever
        harvests a serving lane to feed a HIGHER-priority one."""
        ns, svc_name = key.split("/", 1)

        def mutate(s: InferenceService) -> None:
            if s.spec.pools is not None:
                raise NotFoundError("pooled service: harvest unsupported")
            s.spec.replicas = max(0, int(target_units))
        try:
            self.cluster.update_with_retry(
                InferenceService, ns, svc_name, mutate)
        except NotFoundError:
            return False
        if self.metrics is not None:
            self.metrics.inc("broker_harvests")
        return True

    def _broker_degrade(self, key: str, apply: bool) -> str:
        """The rung-1 pressure valve: flip the service onto a cheaper
        ``DecodePolicy`` variant instead of taking anyone's chips —
        first int8 weights (~half the weight bytes per decode step),
        then deeper speculation when a draft model is configured (more
        accepted tokens per target verify). ``apply=False`` peeks the
        next variant without flipping; '' = nothing left to flip. The
        spec patch rides the same rolling-update machinery as any
        decode-policy edit."""
        ns, svc_name = key.split("/", 1)
        svc = self.cluster.try_get(InferenceService, ns, svc_name)
        if svc is None:
            return ""
        bp = svc.spec.broker
        if bp is not None and not bp.degrade:
            return ""
        dp = (svc.spec.decode or DecodePolicy()).normalized()
        if not dp.int8_weights:
            variant, spec_k = "int8", dp.spec_k
        elif dp.draft_model and dp.spec_k < 8:
            spec_k = min(dp.spec_k * 2, 8)
            variant = f"spec_k:{spec_k}"
        else:
            return ""
        if not apply:
            return variant

        def mutate(s: InferenceService) -> None:
            d = (s.spec.decode or DecodePolicy()).normalized()
            s.spec.decode = DecodePolicy(
                draft_model=d.draft_model, spec_k=spec_k,
                int8_weights=True)
        try:
            self.cluster.update_with_retry(
                InferenceService, ns, svc_name, mutate)
        except NotFoundError:
            return ""
        if self.metrics is not None:
            self.metrics.inc("broker_degrades")
        return variant

    # ------------------------------------------------------------ decision loop
    def run_once(self) -> None:
        with self._lock:
            items = sorted(self._services.items())
        for key, state in items:
            ns, name = key.split("/", 1)
            svc = self.cluster.try_get(InferenceService, ns, name)
            if svc is None or not self._autoscaled(svc):
                if svc is not None:
                    # the service left the autoscaler's care entirely
                    # (autoscale AND slo blocks gone): a lingering
                    # status.slo would be a frozen budget state nobody
                    # will ever update again
                    self._clear_slo_status(svc)
                with self._lock:
                    self._services.pop(key, None)
                self._abandon_loops(state)
                self._broker_deregister(key)
                continue
            try:
                if svc.spec.pools is not None:
                    self._tick_pools(key, svc, state)
                else:
                    self._tick(key, svc, state)
            except NotFoundError:
                continue

    def _tick(self, key: str, svc: InferenceService,
              state: _ServiceState) -> None:
        self._tick_model_slo(key, svc, state)
        if svc.spec.autoscale is None:
            # SLO-only service (``spec.slo`` without ``spec.autoscale``):
            # the tick still scrapes and evaluates — status.slo is the
            # product — but no scaling decision exists to make
            with self._tracer.span("autoscale.tick", svc=key) as sp:
                sample = self._collect(key, svc, state)
                self._tick_slo(key, svc, state, sample, sp)
            return
        self._ensure_policy(svc, state)
        if self.metrics is not None:
            self.metrics.inc("ticks")
        # the kernel template (`controller/loopkernel.py`) drives the
        # observe→decide→commit anatomy and lands one ledger record per
        # decision; the hooks live on _ServiceState above
        state.bind(f"fleetautoscaler/{key}", self.ledger)
        state.bind_owner(self)
        with self._tracer.span("autoscale.tick", svc=key) as sp:
            state.run_tick({"svc": svc, "key": key,
                            "state": state, "span": sp})

    # ------------------------------------------------------------- SLO plane
    @staticmethod
    def _slo_specs(pol) -> List[SLOSpec]:
        """``spec.slo`` (api ``SLOPolicy``) → engine ``SLOSpec``s. The
        api layer's ``normalized()`` already dropped dead objectives, so
        this conversion cannot raise."""
        return [SLOSpec(
            name=o.name, objective=o.objective, target=o.target,
            window_s=o.window_s, fast_short_s=o.fast_short_s,
            fast_long_s=o.fast_long_s, slow_short_s=o.slow_short_s,
            slow_long_s=o.slow_long_s, page_burn=o.page_burn,
            warn_burn=o.warn_burn, hysteresis=o.hysteresis)
            for o in pol.objectives]

    def _clear_slo_status(self, svc: InferenceService) -> None:
        """Blank ``status.slo``: a removed (or normalized-to-nothing)
        policy must not leave a frozen budget state on the CRD — a
        dashboard reading a months-old ``page`` is the exact
        frozen-last-known failure mode the engine's staleness bit
        exists to prevent."""
        if not svc.status.slo:
            return

        def mutate(s: InferenceService) -> None:
            s.status.slo = {}
        try:
            self.cluster.update_with_retry(
                InferenceService, svc.metadata.namespace,
                svc.metadata.name, mutate, subresource="status")
        except NotFoundError:
            pass

    def _ensure_slo(self, key: str, svc: InferenceService,
                    state: _ServiceState) -> bool:
        """(Re)build the service's SLO engine when its ``spec.slo``
        block changes; tear it down — and clear ``status.slo`` — when
        the block is removed or normalizes to zero live objectives.
        Returns whether an engine is live. Window contents do not
        survive a policy edit — stale thresholds interpreting old
        windows would manufacture transitions no event caused."""
        pol = svc.spec.slo
        if pol is None:
            if state.slo_engine is not None or svc.status.slo:
                self._clear_slo_status(svc)
                state.slo_engine = None
                state.slo_key = None
                state.slo_bypass_used = False
                state.slo_written = None
                state.slo_paging = False
                state.slo_live = False
                state.page_up_seq = None
            return False
        norm = pol.normalized()
        skey = tuple(tuple(sorted(vars(o).items()))
                     for o in norm.objectives)
        if state.slo_key != skey:
            state.slo_key = skey
            state.slo_engine = SLOEngine(
                self._slo_specs(norm), clock=self.clock,
                metrics=self.slo_metrics, service=key)
            state.slo_bypass_used = False
            state.slo_written = None
            state.slo_paging = False
            state.slo_live = False
            state.page_up_seq = None
        if not state.slo_engine.evaluators:
            # every objective was junk: nothing will ever evaluate, so
            # any previously-published budget state is dead — clear it
            self._clear_slo_status(svc)
            return False
        return True

    def _feed_slo(self, state: _ServiceState, sample: FleetSample) -> None:
        """One scrape's fresh latency observations into the windows (a
        dead scrape feeds nothing — its absence is what ages the
        windows into staleness)."""
        engine = state.slo_engine
        if engine is None or not sample.ok:
            return
        for kind, values in (("ttft", sample.ttft),
                             ("queue_wait", sample.queue_wait),
                             ("tpot", sample.tpot)):
            for v in values:
                engine.observe_latency(kind, v)

    def _tick_slo(self, key: str, svc: InferenceService,
                  state: _ServiceState, sample: FleetSample,
                  span) -> bool:
        """The SLO half of a tick: feed → evaluate → publish status.slo
        → derive the severity hint. Returns True when a non-stale
        objective is paging AND this page episode has not yet spent its
        one cooldown bypass."""
        if not self._ensure_slo(key, svc, state):
            return False
        self._feed_slo(state, sample)
        return self._evaluate_slo(key, svc, state, span)

    def _evaluate_slo(self, key: str, svc: InferenceService,
                      state: _ServiceState, span) -> bool:
        """Evaluate every objective, publish ``status.slo`` when it
        changed, and return the severity hint (see ``_tick_slo``)."""
        statuses = state.slo_engine.evaluate(span=span)
        burn = max((st.burn_fast for st in statuses.values()
                    if st.burn_fast is not None and not st.stale),
                   default=0.0)
        with self._price_lock:
            self._bid_prices.setdefault(key, {})["burn"] = round(
                max(burn, 0.0), 6)
        rendered = {
            name: SLOObjectiveStatus(
                objective=st.objective, target=st.target, state=st.state,
                burn_fast=(-1.0 if st.burn_fast is None
                           else round(st.burn_fast, 4)),
                burn_slow=(-1.0 if st.burn_slow is None
                           else round(st.burn_slow, 4)),
                budget_remaining=round(st.budget_remaining, 4),
                stale=st.stale)
            for name, st in statuses.items()}
        if rendered != state.slo_written:
            def mutate(s: InferenceService) -> None:
                s.status.slo = rendered
            try:
                self.cluster.update_with_retry(
                    InferenceService, svc.metadata.namespace,
                    svc.metadata.name, mutate, subresource="status")
                state.slo_written = rendered
            except NotFoundError:
                pass
        paging = state.slo_engine.paging(statuses)
        state.slo_live = any(not st.stale for st in statuses.values())
        if paging and not state.slo_paging:
            # paging onset: the episode ordinal is the COUNT of page
            # onsets in the budget log itself, so the ledger's
            # ``slo_page:<svc>#N`` trigger resolves to a real transition
            # line by construction (a resume after a stale gap — no new
            # transition — keeps the original episode's ordinal)
            state.page_episode = len(
                page_onsets(state.slo_engine.event_log)) or 1
        if not paging and state.slo_live \
                and state.page_up_seq is not None:
            # LIVE burn recovery: a non-stale evaluation shows the burn
            # cleared while a page episode is still unanswered (the
            # ``page_up_seq`` marker persists through stale flaps — a
            # signal that merely went dark proves nothing and emits
            # nothing). The event references the scale-up that answered
            # the page — closing its horizon if still open, annotating
            # it otherwise.
            closing = (state.open_horizon is not None
                       and state.open_horizon.seq == state.page_up_seq)
            state.ledger.horizon(state.page_up_seq, loop=state.loop_id,
                                 event=HORIZON_BURN_RECOVERED,
                                 closing=closing)
            if closing:
                state.open_horizon = None
            state.page_up_seq = None
        state.slo_paging = paging
        if not paging:
            state.slo_bypass_used = False   # episode over: re-arm
            return False
        return not state.slo_bypass_used

    # --------------------------------------------------------- per-model SLOs
    def observe_model_latency(self, namespace: str, name: str, model: str,
                              kind: str, seconds: float) -> None:
        """Feed one per-MODEL latency observation (``ttft`` /
        ``queue_wait`` / ``tpot``, seconds) into that model's SLO engine
        — the in-process wiring for multi-model replicas: the pool/twin
        attributes each request to its model and calls this per
        completion. The pod-log scrape plane carries no per-model lines
        yet, so unfed engines age into STALENESS (never zero — the same
        no-data discipline as every other signal here)."""
        key = f"{namespace}/{name}"
        # the engine map AND the engine's windows are guarded by _lock:
        # feeds arrive on caller threads while the tick thread rebuilds
        # the map / evaluates the windows (SLOEngine has no lock of its
        # own)
        with self._lock:
            state = self._services.get(key)
            engine = (state.model_slo.get(model)
                      if state is not None else None)
            if engine is not None:
                engine.observe_latency(kind, seconds)

    def _ensure_model_slo(self, key: str, svc: InferenceService,
                          state: _ServiceState) -> bool:
        """(Re)build the per-model SLO engines when any ref's ``slo``
        block changes — one engine per model carrying objectives, keyed
        ``<service>/<model>`` so the SLO metrics plane labels them
        apart. Same no-carryover rule as the service engine: window
        contents do not survive a policy edit."""
        refs = [m for m in svc.spec.models_normalized()
                if m.slo is not None and m.slo.objectives]
        mkey = tuple(
            (m.name, tuple(tuple(sorted(vars(o).items()))
                           for o in m.slo.objectives))
            for m in refs)
        if state.model_slo_key != mkey:
            engines = {
                m.name: SLOEngine(self._slo_specs(m.slo), clock=self.clock,
                                  metrics=self.slo_metrics,
                                  service=f"{key}/{m.name}")
                for m in refs}
            with self._lock:
                state.model_slo_key = mkey
                state.model_slo = engines
                state.model_slo_written = None
        with self._lock:
            return bool(state.model_slo)

    def _tick_model_slo(self, key: str, svc: InferenceService,
                        state: _ServiceState) -> None:
        """Evaluate every model's objectives and publish them to
        ``status.models[<model>].slo`` — write-on-change, exactly like
        the service-level ``status.slo``. The entry merge is field-
        scoped: the reconciler owns ``image``/``phase``, this tick owns
        ``slo``; neither write clobbers the other's fields."""
        if not self._ensure_model_slo(key, svc, state):
            if state.model_slo_written:
                # per-model SLOs removed: frozen budget states must not
                # linger on the CRD (the model entries themselves stay —
                # they're the reconciler's)
                def clear(s: InferenceService) -> None:
                    for entry in s.status.models.values():
                        entry.slo = {}
                try:
                    self.cluster.update_with_retry(
                        InferenceService, svc.metadata.namespace,
                        svc.metadata.name, clear, subresource="status")
                except NotFoundError:
                    pass
                state.model_slo_written = None
            return
        rendered: Dict[str, Dict[str, SLOObjectiveStatus]] = {}
        with self._lock:
            evaluated = {model: state.model_slo[model].evaluate()
                         for model in sorted(state.model_slo)}
        for model, statuses in evaluated.items():
            rendered[model] = {
                name: SLOObjectiveStatus(
                    objective=st.objective, target=st.target,
                    state=st.state,
                    burn_fast=(-1.0 if st.burn_fast is None
                               else round(st.burn_fast, 4)),
                    burn_slow=(-1.0 if st.burn_slow is None
                               else round(st.burn_slow, 4)),
                    budget_remaining=round(st.budget_remaining, 4),
                    stale=st.stale)
                for name, st in statuses.items()}
        if rendered == state.model_slo_written:
            return

        def mutate(s: InferenceService) -> None:
            for model, slo in rendered.items():
                entry = s.status.models.get(model)
                if entry is None:
                    entry = s.status.models[model] = ModelStatus(name=model)
                entry.slo = slo
        try:
            self.cluster.update_with_retry(
                InferenceService, svc.metadata.namespace,
                svc.metadata.name, mutate, subresource="status")
            state.model_slo_written = rendered
        except NotFoundError:
            pass

    # ------------------------------------------------------------ pool loops
    def _tick_pools(self, key: str, svc: InferenceService,
                    state: _ServiceState) -> None:
        """A disaggregated service runs one decision loop PER POOL —
        queue-wait p95 is the natural SLO for the prefill pool (work
        waiting for a prefill seat), TPOT p95 for the decode pool
        (decode cadence) — each with its own recommender (cooldowns,
        hysteresis, flap damping, slice-legal steps) and its own signal
        window, patching ``spec.pools.<pool>.replicas``. Signals come
        from an attached in-process ``DisaggFleet`` (``pool(name)`` is
        scraped exactly like a fleet); with none attached the window
        goes stale and the policy holds — per-pool log scraping needs
        pool-labelled pods the reconciler does not mint yet."""
        self._tick_model_slo(key, svc, state)
        spec_pools = svc.spec.pools.normalized()
        pools = [p for p in ("prefill", "decode")
                 if getattr(spec_pools, p).autoscale is not None]
        if pools and self.metrics is not None:
            # one tick per service per pass, matching _tick — NOT one
            # per pool, which would make the counter mean different
            # things for pooled vs monolithic services
            self.metrics.inc("ticks")
        # SLO evaluation in pools mode: EVERY pool's scrape feeds the
        # ONE service-level engine (the objectives are service SLOs — a
        # request's TTFT doesn't care which pool served it), evaluated
        # once per pass below. Pools without an autoscale block are
        # scraped too — an SLO-only disagg service must not read as
        # permanently stale just because nothing scales its pools. The
        # page-urgency hint stays a service-loop concern; pool
        # recommenders keep their own SLO targets.
        slo_live = self._ensure_slo(key, svc, state)
        for pool in pools:
            self._tick_one_pool(key, svc, state, pool,
                                getattr(spec_pools, pool))
        if slo_live:
            for pool in ("prefill", "decode"):
                if pool in pools:
                    continue        # its decision tick already fed us
                ps = state.pools.get(pool)
                if ps is None:
                    ps = state.pools[pool] = _PoolState()
                self._feed_slo(state,
                               self._collect_pool(key, state, pool, ps))
            with self._tracer.span("slo.evaluate", svc=key) as sp:
                self._evaluate_slo(key, svc, state, sp)
        if not pools and svc.spec.autoscale is not None:
            # the service registered on its service-level autoscale block,
            # but pools: present hands scaling to the per-pool loops — and
            # neither pool carries one. Without this, migrating a
            # monolithic autoscaled service to disagg while keeping the
            # old block silently stops ALL autoscaling.
            msg = ("pools present: service-level autoscale is ignored; "
                   "set spec.pools.<pool>.autoscale to scale the pools")
            if svc.status.autoscale_message != msg:
                _log.warning("%s for %s", msg, key)

                def mutate(s: InferenceService) -> None:
                    s.status.autoscale_message = msg
                try:
                    self.cluster.update_with_retry(
                        InferenceService, svc.metadata.namespace,
                        svc.metadata.name, mutate, subresource="status")
                except NotFoundError:
                    pass

    def _tick_one_pool(self, key: str, svc: InferenceService,
                       state: _ServiceState, pool: str, pspec) -> None:
        ps = state.pools.get(pool)
        if ps is None:
            ps = state.pools[pool] = _PoolState()
        ap = pspec.autoscale
        pkey = (tuple(sorted(vars(ap).items())),
                svc.spec.tpu_policy.accelerator)
        if ps.policy_key != pkey:
            ps.policy_key = pkey
            ps.recommender = Recommender(
                ap, accelerator=svc.spec.tpu_policy.accelerator)
            ps.aggregator = SignalAggregator(
                window=self.config.autoscale_window_scrapes,
                stale_after=self.config.autoscale_stale_scrapes,
                max_age_s=self._signal_max_age())
        ps.bind(f"fleetautoscaler/{key}/{pool}", self.ledger)
        ps.bind_owner(self)
        with self._tracer.span("autoscale.tick", svc=key, pool=pool) as sp:
            ps.run_tick({"svc": svc, "key": key,
                         "state": state, "pool": pool, "pspec": pspec,
                         "span": sp})

    def _collect_pool(self, key: str, state: _ServiceState, pool: str,
                      ps: _PoolState) -> FleetSample:
        """Pool twin of ``_collect``: scrape the attached fleet's pool
        view; no attached fleet (or a dying one) is an outage — per-pool
        log scraping needs pool-labelled pods the reconciler does not
        mint yet."""
        ps.seq += 1
        ps.tick_chaos_seq = 0
        fault, fault_seq = chaos.fire_seq(chaos.SITE_AUTOSCALE_SIGNAL,
                                          service=key, pool=pool)
        if isinstance(fault, chaos.SignalOutage):
            # the ledger's fault join key: THIS injection's event seq
            # (allocated atomically — a concurrent thread's fault can
            # never be cited by mistake)
            ps.tick_chaos_seq = fault_seq
        fleet, _ = self._fleet_binding(state)
        if not isinstance(fault, chaos.SignalOutage) \
                and fleet is not None and hasattr(fleet, "pool"):
            try:
                return ps.scraper.scrape(fleet.pool(pool), seq=ps.seq)
            # analyze: allow[silent-loss] falls through to the stale_scrapes counter + dead_sample — the outage IS counted
            except Exception:  # noqa: BLE001 — a dying fleet is an outage
                pass
        if self.metrics is not None:
            self.metrics.inc("stale_scrapes")
        return dead_sample(ps.seq)

    # ------------------------------------------------------------- execution
    def _execute(self, key: str, svc: InferenceService,
                 state: _ServiceState, recommender: Recommender,
                 decision, now: float, *, pool: Optional[str] = None
                 ) -> str:
        """The committed half of a decision loop, shared by the service
        and per-pool paths: patch the spec — the commit point, so chaos
        (and real conflicts) before it mean the scale never happened and
        no cooldown is burned; next tick retries at full speed — then
        commit cooldown stamps, publish status + event, and apply to an
        attached in-process fleet. Returns the `obs/ledger` commit
        outcome: ``landed``, ``conflict:<Type>`` (the patch never
        happened), or ``fallback:<Type>`` (the patch landed but the
        in-process fleet apply deferred to the reconciler)."""
        label = key if pool is None else f"{key}/{pool}"
        scope = ((("svc", key),) if pool is None
                 else (("svc", key), ("pool", pool)))
        if self.broker is not None and decision.action == ACTION_UP:
            # the capacity market gate: ask BEFORE the patch, from the
            # same pre-commit position as a chaos fault — a refusal
            # means the scale never happened, no cooldown is burned
            # (``recommender.commit`` below never runs), and the
            # broker's pressure ladder (degrade → harvest → preempt)
            # works the shortfall so next tick's retry can land
            if not self.broker.request_capacity(
                    f"serve/{key}", decision.current, decision.target,
                    urgent=decision.reason.startswith("slo_page"),
                    trigger=(f"slo_page:{key}#{state.page_episode}"
                             if state.slo_paging else "")):
                self.decision_log.append(format_commit_failure_line(
                    decision.seq, "BrokerRefused", scope=scope))
                if self.metrics is not None:
                    self.metrics.inc("patch_failures")
                _log.warning("broker refused %s scale %d -> %d", label,
                             decision.current, decision.target)
                return "conflict:BrokerRefused"
        fault = chaos.fire(chaos.SITE_AUTOSCALE_PATCH, service=label,
                           target=decision.target)
        try:
            if fault is not None:
                raise fault.to_exception()

            def mutate(s: InferenceService) -> None:
                if pool is None:
                    s.spec.replicas = decision.target
                elif s.spec.pools is None:
                    raise NotFoundError("pools block removed")
                else:
                    getattr(s.spec.pools, pool).replicas = decision.target

            self.cluster.update_with_retry(
                InferenceService, svc.metadata.namespace,
                svc.metadata.name, mutate)
        except Exception as e:  # noqa: BLE001 — typed below, loop survives
            self.decision_log.append(format_commit_failure_line(
                decision.seq, type(e).__name__, scope=scope))
            if self.metrics is not None:
                self.metrics.inc("patch_failures")
            _log.warning("replicas patch for %s failed: %s", label, e)
            return f"conflict:{type(e).__name__}"
        recommender.commit(decision, now)
        if self.metrics is not None:
            # the gauge tracks COMMITTED targets only — set after the
            # patch lands, so a failed write never reports a phantom
            # pending scale
            self.metrics.set_gauge("desired_replicas", decision.target,
                                   label=label)

        def mutate_status(s: InferenceService) -> None:
            if pool is None:
                s.status.desired_replicas = decision.target
                s.status.autoscale_message = (
                    f"{decision.action} {decision.current}->"
                    f"{decision.target}: {decision.reason}")
            else:
                s.status.pool_desired_replicas[pool] = decision.target
                s.status.autoscale_message = (
                    f"{pool}: {decision.action} {decision.current}->"
                    f"{decision.target}: {decision.reason}")
        try:
            self.cluster.update_with_retry(
                InferenceService, svc.metadata.namespace,
                svc.metadata.name, mutate_status, subresource="status")
        except NotFoundError:
            pass
        self.cluster.record_event(
            svc, "Normal",
            "AutoscaleReplicas" if pool is None else "AutoscalePoolReplicas",
            ("fleet autoscaler" if pool is None
             else f"fleet autoscaler[{pool}]")
            + f": {decision.current} -> {decision.target} "
            f"({decision.reason})")
        fleet, apply_to_fleet = self._fleet_binding(state)
        if fleet is not None and apply_to_fleet:
            try:
                if pool is None:
                    fleet.scale_to(decision.target)
                else:
                    fleet.scale_pool(pool, decision.target)
            except (RuntimeError, ValueError) as e:
                # a rollout owns desired_replicas right now; the spec
                # patch stands and the reconciler/fleet converge later
                _log.warning("fleet apply for %s (-> %d) deferred: %s",
                             label, decision.target, e)
                return f"fallback:{type(e).__name__}"
        return COMMIT_LANDED

    # --------------------------------------------------------------- signals
    def _signal_max_age(self) -> Optional[float]:
        """Scrape-sample age bound for the aggregators: the configured
        value, a derived default (stale_scrapes worth of tick periods —
        time-staleness engages exactly when count-staleness would have,
        had the ticks kept coming), or None (negative config) to
        disable aging."""
        cfg = self.config.autoscale_signal_max_age_s
        if cfg < 0:
            return None
        if cfg > 0:
            return cfg
        return (self.config.autoscale_stale_scrapes
                * self.config.serving_autoscale_period_seconds)

    def _ensure_policy(self, svc: InferenceService,
                       state: _ServiceState) -> None:
        """(Re)build the recommender/aggregator when the service's
        autoscale block changes — edits apply next tick, but cooldown
        stamps survive an unchanged policy."""
        ap = svc.spec.autoscale
        pkey = (tuple(sorted(vars(ap).items())),
                svc.spec.tpu_policy.accelerator)
        if state.policy_key == pkey:
            return
        state.policy_key = pkey
        state.recommender = Recommender(
            ap, accelerator=svc.spec.tpu_policy.accelerator)
        state.aggregator = SignalAggregator(
            window=self.config.autoscale_window_scrapes,
            stale_after=self.config.autoscale_stale_scrapes,
            max_age_s=self._signal_max_age())

    def _collect(self, key: str, svc: InferenceService,
                 state: _ServiceState) -> FleetSample:
        state.seq += 1   # one monotone counter: dead scrapes count too
        state.tick_chaos_seq = 0
        fault, fault_seq = chaos.fire_seq(chaos.SITE_AUTOSCALE_SIGNAL,
                                          service=key)
        if isinstance(fault, chaos.SignalOutage):
            # THIS injection's event seq (atomic): the decision made
            # under this outage carries a ``chaos#N`` ledger trigger
            state.tick_chaos_seq = fault_seq
            if self.metrics is not None:
                self.metrics.inc("stale_scrapes")
            return dead_sample(state.seq)
        fleet, _ = self._fleet_binding(state)
        if fleet is not None:
            try:
                return state.scraper.scrape(fleet, seq=state.seq)
            # (no allow needed: the handler touches the stale_scrapes
            # counter, which silent-loss accepts as accounting)
            except Exception:  # noqa: BLE001 — a dying fleet is an outage
                if self.metrics is not None:
                    self.metrics.inc("stale_scrapes")
                return dead_sample(state.seq)
        return self._scrape_logs(svc, state)

    def _scrape_logs(self, svc: InferenceService,
                     state: _ServiceState) -> FleetSample:
        """The CRD-plane signal source: tail every replica pod's log for
        observation lines strictly newer than that POD's watermark
        (``batch=`` is the emitter's own step counter — monotone per
        pod, so each line is consumed exactly once; pods start their
        counters independently, so the watermark must be per pod). Each
        pod contributes its newest unseen line; the per-pod samples
        merge into one fleet sample (latencies concatenate, load gauges
        sum). No pod with a new line = a dead scrape: the fleet may be
        gone, or just quiet — staleness, not zero."""
        pods = self.cluster.list(
            Pod, svc.metadata.namespace,
            {constants.LABEL_INFERENCESERVICE_NAME: svc.metadata.name})
        merged: List[FleetSample] = []
        listed = set()
        for pod in sorted(pods, key=lambda p: p.metadata.name):
            listed.add(pod.metadata.name)
            try:
                lines = self.cluster.read_pod_log(
                    pod.metadata.namespace, pod.metadata.name,
                    tail=self.config.autoscale_log_tail)
            except NotFoundError:
                continue
            # newest observation line in the tail = the LAST parseable
            # one (the tail is chronological; the batch counter is NOT
            # globally monotone — it resets when the container restarts)
            newest = -1
            newest_sample = None
            for line in lines:
                mark = line_watermark(line)
                if mark is None:
                    continue
                sample = sample_from_line(line, state.seq)
                if sample is not None:
                    newest, newest_sample = mark, sample
            seen = state.watermark.get(pod.metadata.name, -1)
            # newest > seen: fresh data. newest < seen (but exists): the
            # emitter RESTARTED and its step counter reset — re-anchor
            # instead of going blind until it re-passes the old mark
            # (the log-plane twin of FleetScraper's total<n reset).
            # newest == seen: quiet pod, nothing new.
            if newest_sample is not None and newest != seen:
                state.watermark[pod.metadata.name] = newest
                merged.append(newest_sample)
        # prune departed pods (rollouts mint fresh names every cycle —
        # dead entries both leak and hold poisoned marks for any future
        # pod that reuses the name)
        for name in list(state.watermark):
            if name not in listed:
                del state.watermark[name]
        if not merged:
            if self.metrics is not None:
                self.metrics.inc("stale_scrapes")
            return dead_sample(state.seq)
        return FleetSample(
            seq=state.seq,
            ttft=tuple(v for s in merged for v in s.ttft),
            queue_wait=tuple(v for s in merged for v in s.queue_wait),
            tpot=tuple(v for s in merged for v in s.tpot),
            queue_depth=sum(s.queue_depth for s in merged),
            inflight_tokens=sum(s.inflight_tokens for s in merged),
            slots=sum(s.slots for s in merged),
            ready_replicas=sum(s.ready_replicas for s in merged))

    # ------------------------------------------------------------- recording
    def _record(self, key: str, svc: InferenceService, obs,
                decision, *, pool: Optional[str] = None) -> None:
        """One decision recorded: a stable decision-log line plus the
        observed/decided gauge set — labelled ``ns/name`` for the
        service loop, ``ns/name/pool`` for a pool loop; both export the
        full signal set (every observed gauge is a valid policy input on
        either loop)."""
        label = key if pool is None else f"{key}/{pool}"
        if pool is None:
            # queue pressure per serving slot: the second term of the
            # priced bid's marginal utility (see _serving_bid)
            with self._price_lock:
                self._bid_prices.setdefault(key, {})["queue"] = round(
                    obs.queue_depth / max(obs.slots, 1), 6)
        self.decision_log.append(
            (f"svc={key} " if pool is None else f"svc={key} pool={pool} ")
            + decision.line())
        m = self.metrics
        if m is None:
            return
        m.decision(decision.action)
        if decision.target == decision.current:
            # holds confirm the current size; executed scales update the
            # gauge only once the patch commits (see _execute)
            m.set_gauge("desired_replicas", decision.target, label=label)
        m.set_gauge("current_replicas", decision.current, label=label)
        m.set_gauge("signal_stale", float(obs.stale), label=label)
        if obs.ttft_p95 is not None:
            m.set_gauge("observed_ttft_p95", obs.ttft_p95, label=label)
        if obs.queue_wait_p95 is not None:
            m.set_gauge("observed_queue_wait_p95", obs.queue_wait_p95,
                        label=label)
        if obs.tpot_p95 is not None:
            m.set_gauge("observed_tpot_p95", obs.tpot_p95, label=label)
        m.set_gauge("observed_queue_depth", obs.queue_depth, label=label)
        if obs.tokens_per_slot is not None:
            m.set_gauge("observed_tokens_per_slot", obs.tokens_per_slot,
                        label=label)

    def slo_event_lines(self) -> Dict[str, List[str]]:
        """Per-service SLO budget event logs (the transition lines
        `obs/slo.SLOEngine` appends): what ``--ledger-out`` embeds
        beside the decision records so `tools/why_report.py` can
        resolve ``slo_page:<svc>#N`` triggers to their actual
        ``state=...->page`` transition lines."""
        with self._lock:
            items = sorted(self._services.items())
        out: Dict[str, List[str]] = {}
        for key, state in items:
            engine = state.slo_engine
            if engine is not None and engine.event_log:
                out[key] = list(engine.event_log)
        return out

    # ----------------------------------------------------------------- run loop
    def run(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.run_once()
                except Exception:
                    # same discipline as the elastic loop: a crashing
                    # tick surfaces in the log, never dies silently —
                    # under its own counter, not patch_failures (a
                    # scrape/status/policy crash is not an API write
                    # failure)
                    _log.exception("fleet autoscaler tick failed")
                    if self.metrics is not None:
                        self.metrics.inc("tick_errors")
                self._stop.wait(self.config.serving_autoscale_period_seconds)

        t = threading.Thread(target=loop, daemon=True,
                             name="fleet-autoscaler")
        t.start()
        self._thread = t

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)


def setup_fleet_autoscaler(cluster: InMemoryCluster,
                           config: Optional[JobControllerConfig] = None,
                           metrics: Optional[AutoscaleMetrics] = None,
                           clock: Callable[[], float] = time.monotonic,
                           tracer=None,
                           slo_metrics=None,
                           ledger=None,
                           broker=None) -> FleetAutoscaler:
    """Wire the autoscaler's service registry to the cluster watch (the
    serving twin of ``setup_elastic_autoscaler``)."""
    scaler = FleetAutoscaler(cluster, config=config, metrics=metrics,
                             clock=clock, tracer=tracer,
                             slo_metrics=slo_metrics, ledger=ledger,
                             broker=broker)
    cluster.watch(scaler.observe_event)
    return scaler
