"""FleetAutoscaler: close the fleet-load → ``InferenceService.replicas`` loop.

The serving twin of `controller/autoscaler.ElasticAutoscaler` — a second
control loop over the ``InferenceService`` CRD that *decides* replica
counts from observed serving load, while the existing reconciler
(`controller/inferenceservice.py`) *executes* the resulting spec change
with its surge/drain machinery:

* per registered service (``spec.autoscale`` set), every tick: collect
  one ``FleetSample`` — from an attached in-process ``ServingFleet``
  (`autoscale/signals.FleetScraper` delta-reads its per-replica
  histograms) or by tailing replica pod logs for the extended
  ``[elastic-metrics]`` observation line the fleet prints
  (`serve/fleet.ServingFleet.observation_line`);
* fold the window into a ``FleetObservation``
  (`autoscale/signals.SignalAggregator` — dead scrapes mark the window
  stale, never zero);
* run the deterministic target-tracking policy
  (`autoscale/policy.Recommender`: SLO targets, utilization band,
  slice-legal steps, hysteresis, cooldowns, flap damping, warm floor);
* execute: patch ``spec.replicas`` through the cluster client (the
  reconciler and/or an attached fleet's ``scale_to`` do the rest), write
  the decision into ``status.desired_replicas`` / ``autoscale_message``,
  and append one stable line to ``decision_log`` — the byte-comparable
  artifact `make autoscale-soak` replays.

Failure discipline: a chaos/genuine scrape failure records a dead sample
(staleness holds last-known-good); a failed patch
(``SITE_AUTOSCALE_PATCH``) burns NO cooldown — ``Recommender.commit``
runs only after the write lands — so the loop retries at full speed next
tick instead of sulking through a cooldown it never used.

``run_once()`` is the deterministic unit tests and soak drive; ``run()``
wraps it in a thread at ``serving_autoscale_period_seconds`` cadence,
wired in `main.py` beside the elastic autoscaler.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from tpu_on_k8s import chaos
from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import Pod
from tpu_on_k8s.api.inference_types import (
    InferenceService,
    SLOObjectiveStatus,
)
from tpu_on_k8s.autoscale.policy import ACTION_HOLD, ACTION_UP, Recommender
from tpu_on_k8s.autoscale.signals import (
    FleetSample,
    FleetScraper,
    SignalAggregator,
    dead_sample,
    line_watermark,
    sample_from_line,
)
from tpu_on_k8s.client.cluster import InMemoryCluster, NotFoundError
from tpu_on_k8s.controller.config import JobControllerConfig
from tpu_on_k8s.metrics.metrics import AutoscaleMetrics
from tpu_on_k8s.obs.slo import SLOEngine, SLOSpec
from tpu_on_k8s.obs.trace import ensure as ensure_tracer
from tpu_on_k8s.utils.logging import get_logger

_log = get_logger("fleetautoscaler")


class _PoolState:
    """One pool's decision loop (disaggregated services run two of
    these — prefill and decode — instead of one service-level loop).
    Same anatomy as the service loop: the recommender owns cooldown
    stamps, the aggregator owns the signal window, the scraper owns
    delta-read positions (per pool — the pools' replicas are disjoint,
    but a shared scraper would interleave their sequence numbers)."""

    def __init__(self) -> None:
        self.recommender: Optional[Recommender] = None
        self.policy_key: Optional[Tuple] = None
        self.aggregator: Optional[SignalAggregator] = None
        self.scraper = FleetScraper()
        self.seq = 0


class _ServiceState:
    """Per-service loop state: the policy's cooldown stamps live in the
    recommender; the aggregator owns the signal window; ``fleet`` is the
    optional in-process execution target (single-binary serving)."""

    def __init__(self) -> None:
        self.recommender: Optional[Recommender] = None
        self.policy_key: Optional[Tuple] = None
        self.aggregator: Optional[SignalAggregator] = None
        self.scraper = FleetScraper()
        self.fleet = None
        self.apply_to_fleet = True
        self.seq = 0                 # one counter across live AND dead scrapes
        #: per-pool loops (``spec.pools.<pool>.autoscale`` present)
        self.pools: Dict[str, _PoolState] = {}
        #: newest observation-line batch consumed, PER POD — every pod's
        #: fleet runs its own step counter, so one shared watermark would
        #: permanently blind the scrape to any pod that started later
        self.watermark: Dict[str, int] = {}
        # --- SLO evaluation (``spec.slo`` present; `obs/slo.py`) ---
        self.slo_engine: Optional[SLOEngine] = None
        self.slo_key: Optional[Tuple] = None
        #: one cooldown bypass per page episode: set when a paging
        #: objective's urgency executed a scale-up, cleared when no
        #: objective pages — "bypass the up-cooldown ONCE", dead-banded
        #: by the budget-state hysteresis
        self.slo_bypass_used = False
        #: last rendered status.slo (avoids a status write per tick)
        self.slo_written: Optional[Dict] = None


class FleetAutoscaler:
    """See module doc. One instance watches every autoscaled
    ``InferenceService`` in the cluster."""

    def __init__(self, cluster: InMemoryCluster,
                 config: Optional[JobControllerConfig] = None,
                 metrics: Optional[AutoscaleMetrics] = None,
                 clock: Callable[[], float] = time.monotonic,
                 tracer=None, slo_metrics=None) -> None:
        self.cluster = cluster
        self.config = config or JobControllerConfig()
        self.metrics = metrics
        # the SLO telemetry plane (`metrics.SLOMetrics`): burn-rate /
        # budget gauges + transition counters for every service whose
        # spec carries an ``slo`` block. None → mirror-free evaluation
        # (status.slo still gets written).
        self.slo_metrics = slo_metrics
        self.clock = clock
        # span producer (`tpu_on_k8s/obs/trace.py`): one
        # ``autoscale.tick`` span per (service|pool) decision, carrying
        # the observed signal and the action — the control-plane rows of
        # the same timeline the per-request spans populate. None → NOOP
        # (the decision_log byte-compare sees zero difference).
        self._tracer = ensure_tracer(tracer)
        #: stable one-line-per-decision record (byte-identical across two
        #: runs of the same seeded trace — the autoscale-soak contract).
        #: Bounded: one line per service per tick accrues forever on a
        #: long-lived operator, and a soak fits well inside the cap.
        self.decision_log: Deque[str] = deque(maxlen=10_000)
        self._lock = threading.Lock()
        self._services: Dict[str, _ServiceState] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ registration
    @staticmethod
    def _autoscaled(svc: InferenceService) -> bool:
        """A service participates when its service-level autoscale block
        is set, when it declares SLOs (``spec.slo`` — the tick is what
        evaluates them and writes ``status.slo``, scaling or not), or —
        disaggregated — when either pool carries an autoscale block."""
        if svc.spec.autoscale is not None or svc.spec.slo is not None:
            return True
        pools = svc.spec.pools
        return pools is not None and (
            pools.prefill.autoscale is not None
            or pools.decode.autoscale is not None)

    def register(self, svc: InferenceService) -> None:
        if not self._autoscaled(svc):
            return
        key = f"{svc.metadata.namespace}/{svc.metadata.name}"
        with self._lock:
            self._services.setdefault(key, _ServiceState())

    def deregister(self, svc: InferenceService) -> None:
        key = f"{svc.metadata.namespace}/{svc.metadata.name}"
        with self._lock:
            self._services.pop(key, None)

    def observe_event(self, event) -> None:
        """Watch glue: register on ADDED/MODIFIED (the autoscale block
        may be added to an existing service), deregister on DELETED."""
        if event.kind != constants.KIND_INFERENCESERVICE:
            return
        if event.type in ("ADDED", "MODIFIED"):
            self.register(event.obj)
        elif event.type == "DELETED":
            self.deregister(event.obj)

    def registered(self) -> List[str]:
        with self._lock:
            return sorted(self._services)

    def attach_fleet(self, namespace: str, name: str, fleet, *,
                     apply: bool = True) -> None:
        """Bind an in-process ``ServingFleet`` as both the signal source
        (scraped directly, no log round-trip) and — with ``apply`` — the
        execution target (``fleet.scale_to`` after each committed
        patch). Single-binary deployments and the deterministic
        end-to-end tests use this; the CRD-only path tails pod logs."""
        key = f"{namespace}/{name}"
        with self._lock:
            state = self._services.setdefault(key, _ServiceState())
            state.fleet = fleet
            state.apply_to_fleet = apply

    def _fleet_binding(self, state: _ServiceState):
        """Snapshot ``(fleet, apply_to_fleet)`` under the lock — the
        tick thread reads them while ``attach_fleet`` (main/watch
        thread) rebinds them; a torn read could scrape fleet A and
        apply the decision to fleet B."""
        with self._lock:
            return state.fleet, state.apply_to_fleet

    # ------------------------------------------------------------ decision loop
    def run_once(self) -> None:
        with self._lock:
            items = sorted(self._services.items())
        for key, state in items:
            ns, name = key.split("/", 1)
            svc = self.cluster.try_get(InferenceService, ns, name)
            if svc is None or not self._autoscaled(svc):
                if svc is not None:
                    # the service left the autoscaler's care entirely
                    # (autoscale AND slo blocks gone): a lingering
                    # status.slo would be a frozen budget state nobody
                    # will ever update again
                    self._clear_slo_status(svc)
                with self._lock:
                    self._services.pop(key, None)
                continue
            try:
                if svc.spec.pools is not None:
                    self._tick_pools(key, svc, state)
                else:
                    self._tick(key, svc, state)
            except NotFoundError:
                continue

    def _tick(self, key: str, svc: InferenceService,
              state: _ServiceState) -> None:
        if svc.spec.autoscale is None:
            # SLO-only service (``spec.slo`` without ``spec.autoscale``):
            # the tick still scrapes and evaluates — status.slo is the
            # product — but no scaling decision exists to make
            with self._tracer.span("autoscale.tick", svc=key) as sp:
                sample = self._collect(key, svc, state)
                self._tick_slo(key, svc, state, sample, sp)
            return
        self._ensure_policy(svc, state)
        if self.metrics is not None:
            self.metrics.inc("ticks")

        with self._tracer.span("autoscale.tick", svc=key) as sp:
            sample = self._collect(key, svc, state)
            now = self.clock()
            obs = state.aggregator.record(sample, now=now)
            cur = max(int(svc.spec.replicas), 0)
            # SLO evaluation rides the same tick: feed the fresh scrape,
            # evaluate burn rates, publish status.slo, and derive the
            # severity hint. ``spec.slo`` absent → all of this is a
            # no-op and the decision path below is byte-identical.
            urgent = self._tick_slo(key, svc, state, sample, sp)
            decision = state.recommender.decide(obs, cur, now,
                                                urgent=urgent)
            sp.set(action=decision.action, current=cur,
                   target=decision.target, stale=obs.stale,
                   queue_depth=obs.queue_depth)
            self._record(key, svc, obs, decision)
            if decision.action == ACTION_HOLD or decision.target == cur:
                return
            if urgent and decision.action == ACTION_UP \
                    and decision.reason.startswith("slo_page"):
                # the bypass is spent only when it actually pierced a
                # cooldown (the policy marks those ``slo_page``) — a
                # scale-up that was free anyway must not burn the one
                # escape hatch; it re-arms after the page episode clears
                state.slo_bypass_used = True
            self._execute(key, svc, state, state.recommender, decision, now)

    # ------------------------------------------------------------- SLO plane
    @staticmethod
    def _slo_specs(pol) -> List[SLOSpec]:
        """``spec.slo`` (api ``SLOPolicy``) → engine ``SLOSpec``s. The
        api layer's ``normalized()`` already dropped dead objectives, so
        this conversion cannot raise."""
        return [SLOSpec(
            name=o.name, objective=o.objective, target=o.target,
            window_s=o.window_s, fast_short_s=o.fast_short_s,
            fast_long_s=o.fast_long_s, slow_short_s=o.slow_short_s,
            slow_long_s=o.slow_long_s, page_burn=o.page_burn,
            warn_burn=o.warn_burn, hysteresis=o.hysteresis)
            for o in pol.objectives]

    def _clear_slo_status(self, svc: InferenceService) -> None:
        """Blank ``status.slo``: a removed (or normalized-to-nothing)
        policy must not leave a frozen budget state on the CRD — a
        dashboard reading a months-old ``page`` is the exact
        frozen-last-known failure mode the engine's staleness bit
        exists to prevent."""
        if not svc.status.slo:
            return

        def mutate(s: InferenceService) -> None:
            s.status.slo = {}
        try:
            self.cluster.update_with_retry(
                InferenceService, svc.metadata.namespace,
                svc.metadata.name, mutate, subresource="status")
        except NotFoundError:
            pass

    def _ensure_slo(self, key: str, svc: InferenceService,
                    state: _ServiceState) -> bool:
        """(Re)build the service's SLO engine when its ``spec.slo``
        block changes; tear it down — and clear ``status.slo`` — when
        the block is removed or normalizes to zero live objectives.
        Returns whether an engine is live. Window contents do not
        survive a policy edit — stale thresholds interpreting old
        windows would manufacture transitions no event caused."""
        pol = svc.spec.slo
        if pol is None:
            if state.slo_engine is not None or svc.status.slo:
                self._clear_slo_status(svc)
                state.slo_engine = None
                state.slo_key = None
                state.slo_bypass_used = False
                state.slo_written = None
            return False
        norm = pol.normalized()
        skey = tuple(tuple(sorted(vars(o).items()))
                     for o in norm.objectives)
        if state.slo_key != skey:
            state.slo_key = skey
            state.slo_engine = SLOEngine(
                self._slo_specs(norm), clock=self.clock,
                metrics=self.slo_metrics, service=key)
            state.slo_bypass_used = False
            state.slo_written = None
        if not state.slo_engine.evaluators:
            # every objective was junk: nothing will ever evaluate, so
            # any previously-published budget state is dead — clear it
            self._clear_slo_status(svc)
            return False
        return True

    def _feed_slo(self, state: _ServiceState, sample: FleetSample) -> None:
        """One scrape's fresh latency observations into the windows (a
        dead scrape feeds nothing — its absence is what ages the
        windows into staleness)."""
        engine = state.slo_engine
        if engine is None or not sample.ok:
            return
        for kind, values in (("ttft", sample.ttft),
                             ("queue_wait", sample.queue_wait),
                             ("tpot", sample.tpot)):
            for v in values:
                engine.observe_latency(kind, v)

    def _tick_slo(self, key: str, svc: InferenceService,
                  state: _ServiceState, sample: FleetSample,
                  span) -> bool:
        """The SLO half of a tick: feed → evaluate → publish status.slo
        → derive the severity hint. Returns True when a non-stale
        objective is paging AND this page episode has not yet spent its
        one cooldown bypass."""
        if not self._ensure_slo(key, svc, state):
            return False
        self._feed_slo(state, sample)
        return self._evaluate_slo(key, svc, state, span)

    def _evaluate_slo(self, key: str, svc: InferenceService,
                      state: _ServiceState, span) -> bool:
        """Evaluate every objective, publish ``status.slo`` when it
        changed, and return the severity hint (see ``_tick_slo``)."""
        statuses = state.slo_engine.evaluate(span=span)
        rendered = {
            name: SLOObjectiveStatus(
                objective=st.objective, target=st.target, state=st.state,
                burn_fast=(-1.0 if st.burn_fast is None
                           else round(st.burn_fast, 4)),
                burn_slow=(-1.0 if st.burn_slow is None
                           else round(st.burn_slow, 4)),
                budget_remaining=round(st.budget_remaining, 4),
                stale=st.stale)
            for name, st in statuses.items()}
        if rendered != state.slo_written:
            def mutate(s: InferenceService) -> None:
                s.status.slo = rendered
            try:
                self.cluster.update_with_retry(
                    InferenceService, svc.metadata.namespace,
                    svc.metadata.name, mutate, subresource="status")
                state.slo_written = rendered
            except NotFoundError:
                pass
        paging = state.slo_engine.paging(statuses)
        if not paging:
            state.slo_bypass_used = False   # episode over: re-arm
            return False
        return not state.slo_bypass_used

    # ------------------------------------------------------------ pool loops
    def _tick_pools(self, key: str, svc: InferenceService,
                    state: _ServiceState) -> None:
        """A disaggregated service runs one decision loop PER POOL —
        queue-wait p95 is the natural SLO for the prefill pool (work
        waiting for a prefill seat), TPOT p95 for the decode pool
        (decode cadence) — each with its own recommender (cooldowns,
        hysteresis, flap damping, slice-legal steps) and its own signal
        window, patching ``spec.pools.<pool>.replicas``. Signals come
        from an attached in-process ``DisaggFleet`` (``pool(name)`` is
        scraped exactly like a fleet); with none attached the window
        goes stale and the policy holds — per-pool log scraping needs
        pool-labelled pods the reconciler does not mint yet."""
        spec_pools = svc.spec.pools.normalized()
        pools = [p for p in ("prefill", "decode")
                 if getattr(spec_pools, p).autoscale is not None]
        if pools and self.metrics is not None:
            # one tick per service per pass, matching _tick — NOT one
            # per pool, which would make the counter mean different
            # things for pooled vs monolithic services
            self.metrics.inc("ticks")
        # SLO evaluation in pools mode: EVERY pool's scrape feeds the
        # ONE service-level engine (the objectives are service SLOs — a
        # request's TTFT doesn't care which pool served it), evaluated
        # once per pass below. Pools without an autoscale block are
        # scraped too — an SLO-only disagg service must not read as
        # permanently stale just because nothing scales its pools. The
        # page-urgency hint stays a service-loop concern; pool
        # recommenders keep their own SLO targets.
        slo_live = self._ensure_slo(key, svc, state)
        for pool in pools:
            self._tick_one_pool(key, svc, state, pool,
                                getattr(spec_pools, pool))
        if slo_live:
            for pool in ("prefill", "decode"):
                if pool in pools:
                    continue        # its decision tick already fed us
                ps = state.pools.get(pool)
                if ps is None:
                    ps = state.pools[pool] = _PoolState()
                self._feed_slo(state,
                               self._collect_pool(key, state, pool, ps))
            with self._tracer.span("slo.evaluate", svc=key) as sp:
                self._evaluate_slo(key, svc, state, sp)
        if not pools and svc.spec.autoscale is not None:
            # the service registered on its service-level autoscale block,
            # but pools: present hands scaling to the per-pool loops — and
            # neither pool carries one. Without this, migrating a
            # monolithic autoscaled service to disagg while keeping the
            # old block silently stops ALL autoscaling.
            msg = ("pools present: service-level autoscale is ignored; "
                   "set spec.pools.<pool>.autoscale to scale the pools")
            if svc.status.autoscale_message != msg:
                _log.warning("%s for %s", msg, key)

                def mutate(s: InferenceService) -> None:
                    s.status.autoscale_message = msg
                try:
                    self.cluster.update_with_retry(
                        InferenceService, svc.metadata.namespace,
                        svc.metadata.name, mutate, subresource="status")
                except NotFoundError:
                    pass

    def _tick_one_pool(self, key: str, svc: InferenceService,
                       state: _ServiceState, pool: str, pspec) -> None:
        ps = state.pools.get(pool)
        if ps is None:
            ps = state.pools[pool] = _PoolState()
        ap = pspec.autoscale
        pkey = (tuple(sorted(vars(ap).items())),
                svc.spec.tpu_policy.accelerator)
        if ps.policy_key != pkey:
            ps.policy_key = pkey
            ps.recommender = Recommender(
                ap, accelerator=svc.spec.tpu_policy.accelerator)
            ps.aggregator = SignalAggregator(
                window=self.config.autoscale_window_scrapes,
                stale_after=self.config.autoscale_stale_scrapes,
                max_age_s=self._signal_max_age())

        with self._tracer.span("autoscale.tick", svc=key, pool=pool) as sp:
            sample = self._collect_pool(key, state, pool, ps)
            self._feed_slo(state, sample)
            now = self.clock()
            obs = ps.aggregator.record(sample, now=now)
            cur = max(int(pspec.replicas), 1)
            decision = ps.recommender.decide(obs, cur, now)
            sp.set(action=decision.action, current=cur,
                   target=decision.target, stale=obs.stale,
                   queue_depth=obs.queue_depth)
            self._record(key, svc, obs, decision, pool=pool)
            if decision.action == ACTION_HOLD or decision.target == cur:
                return
            self._execute(key, svc, state, ps.recommender, decision, now,
                          pool=pool)

    def _collect_pool(self, key: str, state: _ServiceState, pool: str,
                      ps: _PoolState) -> FleetSample:
        """Pool twin of ``_collect``: scrape the attached fleet's pool
        view; no attached fleet (or a dying one) is an outage — per-pool
        log scraping needs pool-labelled pods the reconciler does not
        mint yet."""
        ps.seq += 1
        fault = chaos.fire(chaos.SITE_AUTOSCALE_SIGNAL, service=key,
                           pool=pool)
        fleet, _ = self._fleet_binding(state)
        if not isinstance(fault, chaos.SignalOutage) \
                and fleet is not None and hasattr(fleet, "pool"):
            try:
                return ps.scraper.scrape(fleet.pool(pool), seq=ps.seq)
            # analyze: allow[silent-loss] falls through to the stale_scrapes counter + dead_sample — the outage IS counted
            except Exception:  # noqa: BLE001 — a dying fleet is an outage
                pass
        if self.metrics is not None:
            self.metrics.inc("stale_scrapes")
        return dead_sample(ps.seq)

    # ------------------------------------------------------------- execution
    def _execute(self, key: str, svc: InferenceService,
                 state: _ServiceState, recommender: Recommender,
                 decision, now: float, *, pool: Optional[str] = None
                 ) -> None:
        """The committed half of a decision loop, shared by the service
        and per-pool paths: patch the spec — the commit point, so chaos
        (and real conflicts) before it mean the scale never happened and
        no cooldown is burned; next tick retries at full speed — then
        commit cooldown stamps, publish status + event, and apply to an
        attached in-process fleet."""
        label = key if pool is None else f"{key}/{pool}"
        prefix = f"svc={key} " if pool is None \
            else f"svc={key} pool={pool} "
        fault = chaos.fire(chaos.SITE_AUTOSCALE_PATCH, service=label,
                           target=decision.target)
        try:
            if fault is not None:
                raise fault.to_exception()

            def mutate(s: InferenceService) -> None:
                if pool is None:
                    s.spec.replicas = decision.target
                elif s.spec.pools is None:
                    raise NotFoundError("pools block removed")
                else:
                    getattr(s.spec.pools, pool).replicas = decision.target

            self.cluster.update_with_retry(
                InferenceService, svc.metadata.namespace,
                svc.metadata.name, mutate)
        except Exception as e:  # noqa: BLE001 — typed below, loop survives
            self.decision_log.append(
                f"{prefix}seq={decision.seq} patch_failed "
                f"{type(e).__name__}")
            if self.metrics is not None:
                self.metrics.inc("patch_failures")
            _log.warning("replicas patch for %s failed: %s", label, e)
            return
        recommender.commit(decision, now)
        if self.metrics is not None:
            # the gauge tracks COMMITTED targets only — set after the
            # patch lands, so a failed write never reports a phantom
            # pending scale
            self.metrics.set_gauge("desired_replicas", decision.target,
                                   label=label)

        def mutate_status(s: InferenceService) -> None:
            if pool is None:
                s.status.desired_replicas = decision.target
                s.status.autoscale_message = (
                    f"{decision.action} {decision.current}->"
                    f"{decision.target}: {decision.reason}")
            else:
                s.status.pool_desired_replicas[pool] = decision.target
                s.status.autoscale_message = (
                    f"{pool}: {decision.action} {decision.current}->"
                    f"{decision.target}: {decision.reason}")
        try:
            self.cluster.update_with_retry(
                InferenceService, svc.metadata.namespace,
                svc.metadata.name, mutate_status, subresource="status")
        except NotFoundError:
            pass
        self.cluster.record_event(
            svc, "Normal",
            "AutoscaleReplicas" if pool is None else "AutoscalePoolReplicas",
            ("fleet autoscaler" if pool is None
             else f"fleet autoscaler[{pool}]")
            + f": {decision.current} -> {decision.target} "
            f"({decision.reason})")
        fleet, apply_to_fleet = self._fleet_binding(state)
        if fleet is not None and apply_to_fleet:
            try:
                if pool is None:
                    fleet.scale_to(decision.target)
                else:
                    fleet.scale_pool(pool, decision.target)
            except (RuntimeError, ValueError) as e:
                # a rollout owns desired_replicas right now; the spec
                # patch stands and the reconciler/fleet converge later
                _log.warning("fleet apply for %s (-> %d) deferred: %s",
                             label, decision.target, e)

    # --------------------------------------------------------------- signals
    def _signal_max_age(self) -> Optional[float]:
        """Scrape-sample age bound for the aggregators: the configured
        value, a derived default (stale_scrapes worth of tick periods —
        time-staleness engages exactly when count-staleness would have,
        had the ticks kept coming), or None (negative config) to
        disable aging."""
        cfg = self.config.autoscale_signal_max_age_s
        if cfg < 0:
            return None
        if cfg > 0:
            return cfg
        return (self.config.autoscale_stale_scrapes
                * self.config.serving_autoscale_period_seconds)

    def _ensure_policy(self, svc: InferenceService,
                       state: _ServiceState) -> None:
        """(Re)build the recommender/aggregator when the service's
        autoscale block changes — edits apply next tick, but cooldown
        stamps survive an unchanged policy."""
        ap = svc.spec.autoscale
        pkey = (tuple(sorted(vars(ap).items())),
                svc.spec.tpu_policy.accelerator)
        if state.policy_key == pkey:
            return
        state.policy_key = pkey
        state.recommender = Recommender(
            ap, accelerator=svc.spec.tpu_policy.accelerator)
        state.aggregator = SignalAggregator(
            window=self.config.autoscale_window_scrapes,
            stale_after=self.config.autoscale_stale_scrapes,
            max_age_s=self._signal_max_age())

    def _collect(self, key: str, svc: InferenceService,
                 state: _ServiceState) -> FleetSample:
        state.seq += 1   # one monotone counter: dead scrapes count too
        fault = chaos.fire(chaos.SITE_AUTOSCALE_SIGNAL, service=key)
        if isinstance(fault, chaos.SignalOutage):
            if self.metrics is not None:
                self.metrics.inc("stale_scrapes")
            return dead_sample(state.seq)
        fleet, _ = self._fleet_binding(state)
        if fleet is not None:
            try:
                return state.scraper.scrape(fleet, seq=state.seq)
            # (no allow needed: the handler touches the stale_scrapes
            # counter, which silent-loss accepts as accounting)
            except Exception:  # noqa: BLE001 — a dying fleet is an outage
                if self.metrics is not None:
                    self.metrics.inc("stale_scrapes")
                return dead_sample(state.seq)
        return self._scrape_logs(svc, state)

    def _scrape_logs(self, svc: InferenceService,
                     state: _ServiceState) -> FleetSample:
        """The CRD-plane signal source: tail every replica pod's log for
        observation lines strictly newer than that POD's watermark
        (``batch=`` is the emitter's own step counter — monotone per
        pod, so each line is consumed exactly once; pods start their
        counters independently, so the watermark must be per pod). Each
        pod contributes its newest unseen line; the per-pod samples
        merge into one fleet sample (latencies concatenate, load gauges
        sum). No pod with a new line = a dead scrape: the fleet may be
        gone, or just quiet — staleness, not zero."""
        pods = self.cluster.list(
            Pod, svc.metadata.namespace,
            {constants.LABEL_INFERENCESERVICE_NAME: svc.metadata.name})
        merged: List[FleetSample] = []
        listed = set()
        for pod in sorted(pods, key=lambda p: p.metadata.name):
            listed.add(pod.metadata.name)
            try:
                lines = self.cluster.read_pod_log(
                    pod.metadata.namespace, pod.metadata.name,
                    tail=self.config.autoscale_log_tail)
            except NotFoundError:
                continue
            # newest observation line in the tail = the LAST parseable
            # one (the tail is chronological; the batch counter is NOT
            # globally monotone — it resets when the container restarts)
            newest = -1
            newest_sample = None
            for line in lines:
                mark = line_watermark(line)
                if mark is None:
                    continue
                sample = sample_from_line(line, state.seq)
                if sample is not None:
                    newest, newest_sample = mark, sample
            seen = state.watermark.get(pod.metadata.name, -1)
            # newest > seen: fresh data. newest < seen (but exists): the
            # emitter RESTARTED and its step counter reset — re-anchor
            # instead of going blind until it re-passes the old mark
            # (the log-plane twin of FleetScraper's total<n reset).
            # newest == seen: quiet pod, nothing new.
            if newest_sample is not None and newest != seen:
                state.watermark[pod.metadata.name] = newest
                merged.append(newest_sample)
        # prune departed pods (rollouts mint fresh names every cycle —
        # dead entries both leak and hold poisoned marks for any future
        # pod that reuses the name)
        for name in list(state.watermark):
            if name not in listed:
                del state.watermark[name]
        if not merged:
            if self.metrics is not None:
                self.metrics.inc("stale_scrapes")
            return dead_sample(state.seq)
        return FleetSample(
            seq=state.seq,
            ttft=tuple(v for s in merged for v in s.ttft),
            queue_wait=tuple(v for s in merged for v in s.queue_wait),
            tpot=tuple(v for s in merged for v in s.tpot),
            queue_depth=sum(s.queue_depth for s in merged),
            inflight_tokens=sum(s.inflight_tokens for s in merged),
            slots=sum(s.slots for s in merged),
            ready_replicas=sum(s.ready_replicas for s in merged))

    # ------------------------------------------------------------- recording
    def _record(self, key: str, svc: InferenceService, obs,
                decision, *, pool: Optional[str] = None) -> None:
        """One decision recorded: a stable decision-log line plus the
        observed/decided gauge set — labelled ``ns/name`` for the
        service loop, ``ns/name/pool`` for a pool loop; both export the
        full signal set (every observed gauge is a valid policy input on
        either loop)."""
        label = key if pool is None else f"{key}/{pool}"
        self.decision_log.append(
            (f"svc={key} " if pool is None else f"svc={key} pool={pool} ")
            + decision.line())
        m = self.metrics
        if m is None:
            return
        m.decision(decision.action)
        if decision.target == decision.current:
            # holds confirm the current size; executed scales update the
            # gauge only once the patch commits (see _execute)
            m.set_gauge("desired_replicas", decision.target, label=label)
        m.set_gauge("current_replicas", decision.current, label=label)
        m.set_gauge("signal_stale", float(obs.stale), label=label)
        if obs.ttft_p95 is not None:
            m.set_gauge("observed_ttft_p95", obs.ttft_p95, label=label)
        if obs.queue_wait_p95 is not None:
            m.set_gauge("observed_queue_wait_p95", obs.queue_wait_p95,
                        label=label)
        if obs.tpot_p95 is not None:
            m.set_gauge("observed_tpot_p95", obs.tpot_p95, label=label)
        m.set_gauge("observed_queue_depth", obs.queue_depth, label=label)
        if obs.tokens_per_slot is not None:
            m.set_gauge("observed_tokens_per_slot", obs.tokens_per_slot,
                        label=label)

    # ----------------------------------------------------------------- run loop
    def run(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.run_once()
                except Exception:
                    # same discipline as the elastic loop: a crashing
                    # tick surfaces in the log, never dies silently —
                    # under its own counter, not patch_failures (a
                    # scrape/status/policy crash is not an API write
                    # failure)
                    _log.exception("fleet autoscaler tick failed")
                    if self.metrics is not None:
                        self.metrics.inc("tick_errors")
                self._stop.wait(self.config.serving_autoscale_period_seconds)

        t = threading.Thread(target=loop, daemon=True,
                             name="fleet-autoscaler")
        t.start()
        self._thread = t

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)


def setup_fleet_autoscaler(cluster: InMemoryCluster,
                           config: Optional[JobControllerConfig] = None,
                           metrics: Optional[AutoscaleMetrics] = None,
                           clock: Callable[[], float] = time.monotonic,
                           tracer=None,
                           slo_metrics=None) -> FleetAutoscaler:
    """Wire the autoscaler's service registry to the cluster watch (the
    serving twin of ``setup_elastic_autoscaler``)."""
    scaler = FleetAutoscaler(cluster, config=config, metrics=metrics,
                             clock=clock, tracer=tracer,
                             slo_metrics=slo_metrics)
    cluster.watch(scaler.observe_event)
    return scaler
