"""Host-network port management.

Analog of /root/reference/controllers/common/hostnetwork.go: when a job is
annotated ``network-mode=host``, each pod gets a random port from the configured
range; container port and host port are rewritten to it, and the pod's normal
Service is target-port-patched so DNS keeps working (service.go:288-303).

Fixes the reference's container scan bug (hostnetwork.go:54-62 starts at index 1
and can index with ci=-1): we look up the default container by name with a safe
fallback to index 0.
"""
from __future__ import annotations

import random
import threading
from typing import Dict, Optional, Tuple

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import Pod

PortMap = Dict[str, int]  # pod name -> allocated host port


def enabled(annotations: Dict[str, str]) -> bool:
    return annotations.get(constants.ANNOTATION_NETWORK_MODE) == constants.NETWORK_MODE_HOST


def allocate_port(port_range: Tuple[int, int], rng: random.Random | None = None) -> int:
    lo, hi = port_range
    return (rng or random).randint(lo, hi - 1)


class PortAllocator:
    """In-use-aware host-port allocation.

    The reference draws blind from the range (hostnetwork.go:29-43 via
    pod.go:534-535) so two pods on one node can collide; here a port stays
    reserved from allocation until the pod's DELETED watch event releases it.
    Allocation is idempotent per pod key (re-reconciles of the same pod get
    the same port). Random probing keeps allocation O(1) while the range is
    mostly free; a linear sweep guarantees progress near exhaustion.
    """

    def __init__(self, port_range: Tuple[int, int],
                 rng: Optional[random.Random] = None) -> None:
        self._lo, self._hi = port_range
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._by_key: Dict[str, int] = {}  # "ns/pod-name" -> port
        self._in_use: set[int] = set()

    def allocate(self, key: str) -> int:
        with self._lock:
            if key in self._by_key:
                return self._by_key[key]
            if len(self._in_use) >= self._hi - self._lo:
                raise RuntimeError(
                    f"hostnetwork port range {self._lo}-{self._hi} exhausted")
            for _ in range(64):
                port = self._rng.randint(self._lo, self._hi - 1)
                if port not in self._in_use:
                    break
            else:
                port = next(p for p in range(self._lo, self._hi)
                            if p not in self._in_use)
            self._in_use.add(port)
            self._by_key[key] = port
            return port

    def reserve(self, key: str, port: int) -> None:
        """Adopt an existing pod's port (controller restart re-sync)."""
        with self._lock:
            self._by_key[key] = port
            self._in_use.add(port)

    def release(self, key: str) -> None:
        with self._lock:
            port = self._by_key.pop(key, None)
            if port is not None and port not in self._by_key.values():
                self._in_use.discard(port)

    def in_use_count(self) -> int:
        with self._lock:
            return len(self._in_use)


def setup_pod_hostnetwork(pod: Pod, port: int) -> None:
    """Switch the pod to hostNetwork and rewrite the coordinator port
    (hostnetwork.go:47-81, bug-fixed)."""
    pod.spec.host_network = True
    container = pod.spec.default_container()
    if container is None:
        return
    for p in container.ports:
        if p.name == constants.DEFAULT_PORT_NAME:
            p.container_port = port
            p.host_port = port
            return
    # No declared port: add one so the rewrite is still visible to env wiring.
    from tpu_on_k8s.api.core import ContainerPort

    container.ports.append(
        ContainerPort(name=constants.DEFAULT_PORT_NAME, container_port=port, host_port=port)
    )
