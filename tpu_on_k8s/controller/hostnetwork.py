"""Host-network port management.

Analog of /root/reference/controllers/common/hostnetwork.go: when a job is
annotated ``network-mode=host``, each pod gets a random port from the configured
range; container port and host port are rewritten to it, and the pod's normal
Service is target-port-patched so DNS keeps working (service.go:288-303).

Fixes the reference's container scan bug (hostnetwork.go:54-62 starts at index 1
and can index with ci=-1): we look up the default container by name with a safe
fallback to index 0.
"""
from __future__ import annotations

import random
from typing import Dict, Tuple

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import Pod

PortMap = Dict[str, int]  # pod name -> allocated host port


def enabled(annotations: Dict[str, str]) -> bool:
    return annotations.get(constants.ANNOTATION_NETWORK_MODE) == constants.NETWORK_MODE_HOST


def allocate_port(port_range: Tuple[int, int], rng: random.Random | None = None) -> int:
    lo, hi = port_range
    return (rng or random).randint(lo, hi - 1)


def setup_pod_hostnetwork(pod: Pod, port: int) -> None:
    """Switch the pod to hostNetwork and rewrite the coordinator port
    (hostnetwork.go:47-81, bug-fixed)."""
    pod.spec.host_network = True
    container = pod.spec.default_container()
    if container is None:
        return
    for p in container.ports:
        if p.name == constants.DEFAULT_PORT_NAME:
            p.container_port = port
            p.host_port = port
            return
    # No declared port: add one so the rewrite is still visible to env wiring.
    from tpu_on_k8s.api.core import ContainerPort

    container.ports.append(
        ContainerPort(name=constants.DEFAULT_PORT_NAME, container_port=port, host_port=port)
    )
