"""Controller configuration.

Analog of /root/reference/controllers/common/config.go:26-44 (a pflag-set package
global there; an explicit dataclass threaded through constructors here — the
reference's hard-coded tunables from SURVEY §5.6 are surfaced as fields).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple


@dataclass
class JobControllerConfig:
    enable_gang_scheduling: bool = True
    max_concurrent_reconciles: int = 1
    sync_period_seconds: float = 30.0
    hostnetwork_port_range: Tuple[int, int] = (20000, 30000)
    model_image_builder: str = "gcr.io/kaniko-project/executor:latest"

    # Surfaced tunables (hard-coded in the reference — SURVEY §5.6):
    coordinator_period_seconds: float = 0.1        # plugins/registry.go:27
    quota_assume_ttl_seconds: float = 60.0         # plugins/quota.go:48
    elastic_loop_period_seconds: float = 30.0      # elastictorchjob_controller.go:60
    elastic_metric_count: int = 5
    # Profiling hooks (tpu_on_k8s/utils/profiling.py): when set, the TPUJob
    # reconciler injects TPU_ON_K8S_PROFILE_DIR / TPU_ON_K8S_PROFILER_PORT
    # into every slice-host pod and `train/loop.py` activates XLA trace
    # capture / the live profiler server. Empty/zero (the default) injects
    # nothing — behavior-neutral.
    profile_dir: str = ""
    profiler_port: int = 0
    # Serving autoscaler (controller/fleetautoscaler.py): tick period,
    # scrapes aggregated per observation window, consecutive dead scrapes
    # before the signal is stale (hold, don't scale), and the pod-log tail
    # depth the out-of-process signal source reads per tick.
    serving_autoscale_period_seconds: float = 15.0
    autoscale_window_scrapes: int = 4
    autoscale_stale_scrapes: int = 3
    autoscale_log_tail: int = 20
    # Time-based staleness on the scrape window (autoscale/signals.py
    # SignalAggregator max_age_s): samples older than this stop
    # contributing, so a clock jump past the whole window surfaces as
    # STALE instead of acting on ancient data. 0 derives the default —
    # stale_scrapes worth of tick periods; negative disables aging.
    autoscale_signal_max_age_s: float = 0.0
    # Consecutive autoscaler ticks tolerating Pending pods at a grown size
    # before reverting (the reference polls up to 1min, elastic_scale.go:440).
    elastic_pending_grace_ticks: int = 2
    # Reconcile passes the elastic controller HOLDS the world for a
    # pending live-reshard ack before giving up (the pod-side agent died
    # mid-transform without clearing the request): past this, the
    # request is withdrawn and the cold checkpoint-restart path runs.
    # Pass-counted in controller memory, not clock-based — deterministic.
    reshard_hold_max_passes: int = 40
    failover_concurrency: int = 50                 # failover.go semaphore widths
    # TPU-first: one dead host kills its slice's SPMD program — restart the
    # slice's surviving workers together (SURVEY §5.3 TPU note).
    slice_atomic_failover: bool = True
    scale_concurrency: int = 100                   # elastic_scale.go:258
    victim_cleanup_concurrency: int = 10           # elastic_scale.go:492
    expectation_ttl_seconds: float = 300.0
