"""Pod-failure classification and failover actions.

Analog of /root/reference/controllers/common/failover.go — the exit-code taxonomy
(:64-99), retryable kill reasons (:106-113), the ``shouldPodFailover`` predicate
(:52-61, only under RestartPolicy.ON_EXIT_CODE), and the two recovery actions:
recreate (delete + let the engine recreate) and in-place restart (the OpenKruise
ContainerRecreateRequest protocol, abstracted behind ``InPlaceRestarter`` so a
GKE backend can post real CRRs while tests use the in-memory executor).

TPU note (SURVEY §5.3): TPU-VM preemption surfaces as an Evicted/Killed pod; it
classifies as retryable here, and slice-atomicity is enforced one level up — a
failed host invalidates its whole slice's gang, so the engine fails over the
slice's task group, not just the single pod.
"""
from __future__ import annotations

import enum
from typing import Optional, Protocol

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import Pod, PodPhase, utcnow
from tpu_on_k8s.api.types import RestartPolicy
from tpu_on_k8s.client.cluster import InMemoryCluster, NotFoundError

# Exit-code taxonomy (failover.go:64-99).
PERMANENT_EXIT_CODES = {1, 2, 126, 127, 128, 139}
RETRYABLE_EXIT_CODES = {130, 137, 143}
USER_DEFINED_RETRYABLE_EXIT_CODE = 138

# Pod kill reasons that retry regardless of exit code (failover.go:106-113).
RETRYABLE_REASONS = {"OOMKilled", "Killed", "Evicted", "UnexpectedAdmissionError"}


class ExitClass(str, enum.Enum):
    PERMANENT = "permanent"
    RETRYABLE = "retryable"
    USER_RETRYABLE = "user-retryable"
    UNKNOWN = "unknown"


def classify_exit_code(code: int) -> ExitClass:
    if code == USER_DEFINED_RETRYABLE_EXIT_CODE:
        return ExitClass.USER_RETRYABLE
    if code in RETRYABLE_EXIT_CODES:
        return ExitClass.RETRYABLE
    if code in PERMANENT_EXIT_CODES:
        return ExitClass.PERMANENT
    return ExitClass.UNKNOWN


def pod_exit_code(pod: Pod) -> Optional[int]:
    """Highest-signal terminated exit code across containers (the reference
    captures the first non-zero main-container code)."""
    best: Optional[int] = None
    for cs in pod.status.container_statuses:
        if cs.terminated is not None:
            code = cs.terminated.exit_code
            if code != 0:
                return code
            best = code
    return best


def should_pod_failover(pod: Pod, restart_policy: RestartPolicy) -> bool:
    """True if a Failed pod should be recovered rather than counted as a
    permanent failure (failover.go:52-61). Only RestartPolicy.ON_EXIT_CODE
    consults the taxonomy; OnFailure always retries; Never/Always do not
    failover here (Always is handled by the kubelet)."""
    if pod.status.phase != PodPhase.FAILED:
        return False
    if restart_policy == RestartPolicy.ON_FAILURE:
        return True
    if restart_policy != RestartPolicy.ON_EXIT_CODE:
        return False
    if pod.status.reason in RETRYABLE_REASONS:
        return True
    code = pod_exit_code(pod)
    if code is None:
        return False
    return classify_exit_code(code) in (ExitClass.RETRYABLE, ExitClass.USER_RETRYABLE)


class InPlaceRestarter(Protocol):
    """CRR executor seam (failover.go:210-307). Returns True on success; the
    caller falls back to delete+recreate on failure (:242-247)."""

    def restart(self, cluster: InMemoryCluster, pod: Pod) -> bool: ...


class InMemoryRestarter:
    """Test/local executor: resets the pod to Running in place and bumps
    restart counts — what the kruise daemon's CRI restart looks like from the
    API server's perspective."""

    def restart(self, cluster: InMemoryCluster, pod: Pod) -> bool:
        def mutate(p: Pod) -> None:
            p.status.phase = PodPhase.RUNNING
            p.status.reason = ""
            for cs in p.status.container_statuses:
                cs.ready = True
                cs.restart_count += 1
                cs.terminated = None

        try:
            cluster.update_with_retry(
                Pod, pod.metadata.namespace, pod.metadata.name, mutate,
                subresource="status")
            return True
        except NotFoundError:
            return False


def failover_recreate(cluster: InMemoryCluster, pod: Pod) -> bool:
    """Delete the failed pod; the engine's next reconcile recreates it
    (failover.go:149-172). Stamps the failover timestamp annotation first.
    Returns False if the pod was already gone (caller must drain any deletion
    expectation it raised)."""
    try:
        cluster.patch_meta(
            Pod, pod.metadata.namespace, pod.metadata.name,
            annotations={constants.ANNOTATION_LAST_FAILOVER_TIMESTAMP: utcnow().isoformat()},
            # The victim must actually go away: failover delete overrides the
            # preempt-protector (it is not a preemption).
            remove_finalizers=[constants.FINALIZER_PREEMPT_PROTECTOR],
        )
        cluster.delete(Pod, pod.metadata.namespace, pod.metadata.name)
        return True
    except NotFoundError:
        return False


def failover_inplace_restart(
    cluster: InMemoryCluster, pod: Pod, restarter: Optional[InPlaceRestarter]
) -> bool:
    """In-place restart via the CRR seam, falling back to recreate
    (failover.go:210-264). Returns True iff the pod was restarted in place
    (False means a recreate happened or the pod vanished)."""
    if restarter is not None and restarter.restart(cluster, pod):
        return True
    failover_recreate(cluster, pod)
    return False
