"""Pod-failure classification and failover actions.

Analog of /root/reference/controllers/common/failover.go — the exit-code taxonomy
(:64-99), retryable kill reasons (:106-113), the ``shouldPodFailover`` predicate
(:52-61, only under RestartPolicy.ON_EXIT_CODE), and the two recovery actions:
recreate (delete + let the engine recreate) and in-place restart (the OpenKruise
ContainerRecreateRequest protocol, abstracted behind ``InPlaceRestarter`` so a
GKE backend can post real CRRs while tests use the in-memory executor).

TPU note (SURVEY §5.3): TPU-VM preemption surfaces as an Evicted/Killed pod; it
classifies as retryable here, and slice-atomicity is enforced one level up — a
failed host invalidates its whole slice's gang, so the engine fails over the
slice's task group, not just the single pod.
"""
from __future__ import annotations

import enum
import time
from typing import Optional, Protocol

from tpu_on_k8s.api import constants, crr as crr_api
from tpu_on_k8s.api.core import ObjectMeta, OwnerReference, Pod, PodPhase, utcnow
from tpu_on_k8s.api.crr import ContainerRecreateRequest
from tpu_on_k8s.api.types import RestartPolicy
from tpu_on_k8s.client.cluster import (
    AlreadyExistsError,
    InMemoryCluster,
    NotFoundError,
)

# Exit-code taxonomy (failover.go:64-99).
PERMANENT_EXIT_CODES = {1, 2, 126, 127, 128, 139}
RETRYABLE_EXIT_CODES = {130, 137, 143}
USER_DEFINED_RETRYABLE_EXIT_CODE = 138

# Pod kill reasons that retry regardless of exit code (failover.go:106-113).
RETRYABLE_REASONS = {"OOMKilled", "Killed", "Evicted", "UnexpectedAdmissionError"}


class ExitClass(str, enum.Enum):
    PERMANENT = "permanent"
    RETRYABLE = "retryable"
    USER_RETRYABLE = "user-retryable"
    UNKNOWN = "unknown"


def classify_exit_code(code: int) -> ExitClass:
    if code == USER_DEFINED_RETRYABLE_EXIT_CODE:
        return ExitClass.USER_RETRYABLE
    if code in RETRYABLE_EXIT_CODES:
        return ExitClass.RETRYABLE
    if code in PERMANENT_EXIT_CODES:
        return ExitClass.PERMANENT
    return ExitClass.UNKNOWN


def pod_exit_code(pod: Pod) -> Optional[int]:
    """Highest-signal terminated exit code across containers (the reference
    captures the first non-zero main-container code)."""
    best: Optional[int] = None
    for cs in pod.status.container_statuses:
        if cs.terminated is not None:
            code = cs.terminated.exit_code
            if code != 0:
                return code
            best = code
    return best


def should_pod_failover(pod: Pod, restart_policy: RestartPolicy) -> bool:
    """True if a Failed pod should be recovered rather than counted as a
    permanent failure (failover.go:52-61). Only RestartPolicy.ON_EXIT_CODE
    consults the taxonomy; OnFailure always retries; Never/Always do not
    failover here (Always is handled by the kubelet)."""
    if pod.status.phase != PodPhase.FAILED:
        return False
    if restart_policy == RestartPolicy.ON_FAILURE:
        return True
    if restart_policy != RestartPolicy.ON_EXIT_CODE:
        return False
    if pod.status.reason in RETRYABLE_REASONS:
        return True
    code = pod_exit_code(pod)
    if code is None:
        return False
    return classify_exit_code(code) in (ExitClass.RETRYABLE, ExitClass.USER_RETRYABLE)


class InPlaceRestarter(Protocol):
    """CRR executor seam (failover.go:210-307). Returns True on success; the
    caller falls back to delete+recreate on failure (:242-247)."""

    def restart(self, cluster: InMemoryCluster, pod: Pod) -> bool: ...


class InMemoryRestarter:
    """Test/local executor: resets the pod to Running in place and bumps
    restart counts — what the kruise daemon's CRI restart looks like from the
    API server's perspective. Only legitimate against the in-memory backend,
    where no kubelet owns pod status; ``main.build_restarter`` selects
    ``CRRRestarter`` for any real (REST) cluster."""

    def restart(self, cluster: InMemoryCluster, pod: Pod) -> bool:
        def mutate(p: Pod) -> None:
            p.status.phase = PodPhase.RUNNING
            p.status.reason = ""
            for cs in p.status.container_statuses:
                cs.ready = True
                cs.restart_count += 1
                cs.terminated = None

        try:
            cluster.update_with_retry(
                Pod, pod.metadata.namespace, pod.metadata.name, mutate,
                subresource="status")
            return True
        except NotFoundError:
            return False


class CRRRestarter:
    """Kruise-protocol executor (failover.go:210-307): post a
    ``ContainerRecreateRequest`` and let the NODE AGENT restart the
    containers — the operator never writes kubelet-owned pod status.

    The reference's protocol is level-triggered across reconcile passes;
    this repo's ``InPlaceRestarter`` seam is a synchronous bool, so the
    state machine is driven here with a bounded poll instead of across
    reconciles — same states, same transitions:

    * CRR named after the pod, labeled with the pod uid; a stale-uid CRR
      (older incarnation) is deleted and re-posted (failover.go:231-237);
    * ``Failed`` ⇒ delete the CRR, return False — the caller falls back to
      delete+recreate (failover.go:242-247);
    * ``Succeeded`` ⇒ delete the CRR (restarts are repeatable; the name
      must free up, failover.go:258-262), return True;
    * deadline (no node agent alive / node dead) ⇒ best-effort delete,
      return False — recreate is the safe degraded path: on a real cluster
      a dead kruise daemon usually means a dead node.
    """

    def __init__(self, cluster: InMemoryCluster, *,
                 wait_seconds: float = 5.0, poll_seconds: float = 0.05):
        self.cluster = cluster
        self.wait_seconds = wait_seconds
        self.poll_seconds = poll_seconds

    def _post(self, pod: Pod) -> None:
        req = ContainerRecreateRequest(
            metadata=ObjectMeta(
                name=pod.metadata.name,
                namespace=pod.metadata.namespace,
                labels={crr_api.LABEL_CRR_POD_UID: pod.metadata.uid},
                owner_references=[OwnerReference(
                    api_version="v1", kind="Pod", name=pod.metadata.name,
                    uid=pod.metadata.uid, controller=False,
                    block_owner_deletion=True)],
            ),
            spec=crr_api.ContainerRecreateRequestSpec(
                pod_name=pod.metadata.name,
                containers=[c.name for c in pod.spec.containers],
                ttl_seconds_after_finished=300.0,
            ),
        )
        try:
            self.cluster.create(req)
        except AlreadyExistsError:
            pass  # another reconcile won the race; adopt theirs

    def _delete(self, namespace: str, name: str) -> None:
        try:
            self.cluster.delete(ContainerRecreateRequest, namespace, name)
        except NotFoundError:
            pass

    def restart(self, cluster: InMemoryCluster, pod: Pod) -> bool:
        del cluster  # protocol seam passes it; this executor owns its client
        ns, name = pod.metadata.namespace, pod.metadata.name
        deadline = time.monotonic() + self.wait_seconds
        posted = False
        while True:
            req = self.cluster.try_get(ContainerRecreateRequest, ns, name)
            if req is None:
                if posted and self.cluster.try_get(Pod, ns, name) is None:
                    return False  # pod vanished; nothing to restart
                self._post(pod)
                posted = True
            elif (req.metadata.labels.get(crr_api.LABEL_CRR_POD_UID)
                  != pod.metadata.uid):
                self._delete(ns, name)  # stale incarnation's CRR
            elif req.status.phase == crr_api.PHASE_FAILED:
                self._delete(ns, name)
                return False
            elif req.status.phase == crr_api.PHASE_SUCCEEDED:
                self._delete(ns, name)
                return True
            if time.monotonic() >= deadline:
                # leave no orphan that could fire after our recreate fallback
                self._delete(ns, name)
                return False
            time.sleep(self.poll_seconds)


def failover_recreate(cluster: InMemoryCluster, pod: Pod) -> bool:
    """Delete the failed pod; the engine's next reconcile recreates it
    (failover.go:149-172). Stamps the failover timestamp annotation first.
    Returns False if the pod was already gone (caller must drain any deletion
    expectation it raised)."""
    try:
        cluster.patch_meta(
            Pod, pod.metadata.namespace, pod.metadata.name,
            annotations={constants.ANNOTATION_LAST_FAILOVER_TIMESTAMP: utcnow().isoformat()},
            # The victim must actually go away: failover delete overrides the
            # preempt-protector (it is not a preemption).
            remove_finalizers=[constants.FINALIZER_PREEMPT_PROTECTOR],
        )
        cluster.delete(Pod, pod.metadata.namespace, pod.metadata.name)
        return True
    except NotFoundError:
        return False


def failover_inplace_restart(
    cluster: InMemoryCluster, pod: Pod, restarter: Optional[InPlaceRestarter]
) -> bool:
    """In-place restart via the CRR seam, falling back to recreate
    (failover.go:210-264). Returns True iff the pod was restarted in place
    (False means a recreate happened or the pod vanished)."""
    if restarter is not None and restarter.restart(cluster, pod):
        return True
    failover_recreate(cluster, pod)
    return False
