"""Pod-failure classification and failover actions.

Analog of /root/reference/controllers/common/failover.go — the exit-code taxonomy
(:64-99), retryable kill reasons (:106-113), the ``shouldPodFailover`` predicate
(:52-61, only under RestartPolicy.ON_EXIT_CODE), and the two recovery actions:
recreate (delete + let the engine recreate) and in-place restart (the OpenKruise
ContainerRecreateRequest protocol, abstracted behind ``InPlaceRestarter`` so a
GKE backend can post real CRRs while tests use the in-memory executor).

TPU note (SURVEY §5.3): TPU-VM preemption surfaces as an Evicted/Killed pod; it
classifies as retryable here, and slice-atomicity is enforced one level up — a
failed host invalidates its whole slice's gang, so the engine fails over the
slice's task group, not just the single pod.
"""
from __future__ import annotations

import enum
from typing import Optional, Protocol

from tpu_on_k8s.api import constants, crr as crr_api
from tpu_on_k8s.api.core import ObjectMeta, OwnerReference, Pod, PodPhase, utcnow
from tpu_on_k8s.api.crr import ContainerRecreateRequest
from tpu_on_k8s.api.types import RestartPolicy
from tpu_on_k8s.client.cluster import (
    AlreadyExistsError,
    InMemoryCluster,
    NotFoundError,
)

# Exit-code taxonomy (failover.go:64-99).
PERMANENT_EXIT_CODES = {1, 2, 126, 127, 128, 139}
RETRYABLE_EXIT_CODES = {130, 137, 143}
USER_DEFINED_RETRYABLE_EXIT_CODE = 138

# Pod kill reasons that retry regardless of exit code (failover.go:106-113).
RETRYABLE_REASONS = {"OOMKilled", "Killed", "Evicted", "UnexpectedAdmissionError"}


class ExitClass(str, enum.Enum):
    PERMANENT = "permanent"
    RETRYABLE = "retryable"
    USER_RETRYABLE = "user-retryable"
    UNKNOWN = "unknown"


def classify_exit_code(code: int) -> ExitClass:
    if code == USER_DEFINED_RETRYABLE_EXIT_CODE:
        return ExitClass.USER_RETRYABLE
    if code in RETRYABLE_EXIT_CODES:
        return ExitClass.RETRYABLE
    if code in PERMANENT_EXIT_CODES:
        return ExitClass.PERMANENT
    return ExitClass.UNKNOWN


def pod_exit_code(pod: Pod) -> Optional[int]:
    """Highest-signal terminated exit code across containers (the reference
    captures the first non-zero main-container code)."""
    best: Optional[int] = None
    for cs in pod.status.container_statuses:
        if cs.terminated is not None:
            code = cs.terminated.exit_code
            if code != 0:
                return code
            best = code
    return best


def should_pod_failover(pod: Pod, restart_policy: RestartPolicy) -> bool:
    """True if a Failed pod should be recovered rather than counted as a
    permanent failure (failover.go:52-61). Only RestartPolicy.ON_EXIT_CODE
    consults the taxonomy; OnFailure always retries; Never/Always do not
    failover here (Always is handled by the kubelet)."""
    if pod.status.phase != PodPhase.FAILED:
        return False
    if restart_policy == RestartPolicy.ON_FAILURE:
        return True
    if restart_policy != RestartPolicy.ON_EXIT_CODE:
        return False
    if pod.status.reason in RETRYABLE_REASONS:
        return True
    code = pod_exit_code(pod)
    if code is None:
        return False
    return classify_exit_code(code) in (ExitClass.RETRYABLE, ExitClass.USER_RETRYABLE)


class RestartOutcome(enum.Enum):
    """Level-triggered in-place-restart protocol states. ``PENDING`` means a
    CRR is in flight and the caller must re-drive on a later reconcile pass
    — never block a reconcile waiting for a node agent. Truthiness follows
    the old bool seam: only a completed restart is truthy."""

    RESTARTED = "restarted"
    PENDING = "pending"
    FAILED = "failed"

    def __bool__(self) -> bool:
        return self is RestartOutcome.RESTARTED


class InPlaceRestarter(Protocol):
    """CRR executor seam (failover.go:210-307). Returns a ``RestartOutcome``
    (or a legacy bool — normalized by ``failover_inplace_restart``); on
    FAILED the caller falls back to delete+recreate (:242-247)."""

    def restart(self, cluster: InMemoryCluster, pod: Pod): ...


class InMemoryRestarter:
    """Test/local executor: resets the pod to Running in place and bumps
    restart counts — what the kruise daemon's CRI restart looks like from the
    API server's perspective. Only legitimate against the in-memory backend,
    where no kubelet owns pod status; ``main.build_restarter`` selects
    ``CRRRestarter`` for any real (REST) cluster."""

    def restart(self, cluster: InMemoryCluster, pod: Pod) -> RestartOutcome:
        def mutate(p: Pod) -> None:
            p.status.phase = PodPhase.RUNNING
            p.status.reason = ""
            for cs in p.status.container_statuses:
                cs.ready = True
                cs.restart_count += 1
                cs.terminated = None

        try:
            cluster.update_with_retry(
                Pod, pod.metadata.namespace, pod.metadata.name, mutate,
                subresource="status")
            return RestartOutcome.RESTARTED
        except NotFoundError:
            return RestartOutcome.FAILED


class CRRRestarter:
    """Kruise-protocol executor (failover.go:210-307): post a
    ``ContainerRecreateRequest`` and let the NODE AGENT restart the
    containers — the operator never writes kubelet-owned pod status.

    LEVEL-TRIGGERED, like the reference: each ``restart`` call makes ONE
    observation of the CRR and returns immediately — ``PENDING`` while the
    node agent works, so a whole failing slice costs a reconcile pass
    O(n × API-roundtrip), never O(n × node-agent-latency). The round-4
    executor blocked the reconcile up to ``wait_seconds`` per pod
    (VERDICT r4 weak: a v5e-16 slice serialized ~4×5 s of stall); now
    ``wait_seconds`` is a deadline measured from the CRR's
    creationTimestamp ACROSS passes, not an in-pass poll. States:

    * no CRR ⇒ post one (named after the pod, labeled with the pod uid),
      return PENDING;
    * stale-uid CRR (older pod incarnation) ⇒ delete, return PENDING
      (re-posted next pass, failover.go:231-237);
    * ``Failed`` ⇒ delete the CRR, return FAILED — the caller falls back to
      delete+recreate (failover.go:242-247);
    * ``Succeeded`` with the pod actually Running ⇒ delete the CRR
      (restarts are repeatable; the name must free up, failover.go:258-262),
      return RESTARTED. A Succeeded CRR whose pod is NOT Running is a stale
      leftover from an earlier incident (e.g. an uncollected slice-sibling
      restart) — deleted, PENDING, so a fresh CRR drives the real restart;
    * CRR older than ``wait_seconds`` (no node agent alive / node dead) ⇒
      delete, return FAILED — recreate is the safe degraded path: on a real
      cluster a dead kruise daemon usually means a dead node.
    """

    def __init__(self, cluster: InMemoryCluster, *,
                 wait_seconds: float = 60.0):
        self.cluster = cluster
        self.wait_seconds = wait_seconds

    def _post(self, pod: Pod) -> None:
        labels = {crr_api.LABEL_CRR_POD_UID: pod.metadata.uid}
        job_name = pod.metadata.labels.get(constants.LABEL_JOB_NAME, "")
        if job_name:
            # the job label lets the operator's watch requeue the owning job
            # when the node agent updates the CRR phase (level-triggered
            # protocols advance on events, not on polling)
            labels[constants.LABEL_JOB_NAME] = job_name
        req = ContainerRecreateRequest(
            metadata=ObjectMeta(
                name=pod.metadata.name,
                namespace=pod.metadata.namespace,
                labels=labels,
                owner_references=[OwnerReference(
                    api_version="v1", kind="Pod", name=pod.metadata.name,
                    uid=pod.metadata.uid, controller=False,
                    block_owner_deletion=True)],
            ),
            spec=crr_api.ContainerRecreateRequestSpec(
                pod_name=pod.metadata.name,
                containers=[c.name for c in pod.spec.containers],
                ttl_seconds_after_finished=300.0,
            ),
        )
        try:
            self.cluster.create(req)
        except AlreadyExistsError:
            pass  # another reconcile won the race; adopt theirs

    def _delete(self, namespace: str, name: str) -> None:
        try:
            self.cluster.delete(ContainerRecreateRequest, namespace, name)
        except NotFoundError:
            pass

    def restart(self, cluster: InMemoryCluster, pod: Pod) -> RestartOutcome:
        del cluster  # protocol seam passes it; this executor owns its client
        ns, name = pod.metadata.namespace, pod.metadata.name
        req = self.cluster.try_get(ContainerRecreateRequest, ns, name)
        if req is None:
            if self.cluster.try_get(Pod, ns, name) is None:
                return RestartOutcome.FAILED  # pod vanished; nothing to do
            self._post(pod)
            return RestartOutcome.PENDING
        if (req.metadata.labels.get(crr_api.LABEL_CRR_POD_UID)
                != pod.metadata.uid):
            self._delete(ns, name)  # stale incarnation's CRR
            return RestartOutcome.PENDING
        if req.status.phase == crr_api.PHASE_FAILED:
            self._delete(ns, name)
            return RestartOutcome.FAILED
        if req.status.phase == crr_api.PHASE_SUCCEEDED:
            self._delete(ns, name)
            live = self.cluster.try_get(Pod, ns, name)
            if live is not None and live.status.phase == PodPhase.RUNNING:
                return RestartOutcome.RESTARTED
            # stale success (pod failed again, or success from an earlier
            # uncollected incident): a fresh CRR drives the real restart
            return RestartOutcome.PENDING
        created = req.metadata.creation_timestamp
        age = ((utcnow() - created).total_seconds()
               if created is not None else 0.0)
        if age >= self.wait_seconds:
            # leave no orphan that could fire after our recreate fallback
            self._delete(ns, name)
            return RestartOutcome.FAILED
        return RestartOutcome.PENDING

    def collect(self, pod: Pod) -> Optional[RestartOutcome]:
        """Observe-only: settle an in-flight CRR WITHOUT ever posting a new
        one. Used to re-drive fire-and-forget restarts (slice siblings) —
        consuming their Succeeded/Failed CRRs so the name frees up without
        risking a posting loop. Returns None when no CRR for this pod
        incarnation exists."""
        ns, name = pod.metadata.namespace, pod.metadata.name
        req = self.cluster.try_get(ContainerRecreateRequest, ns, name)
        if req is None or (req.metadata.labels.get(crr_api.LABEL_CRR_POD_UID)
                           != pod.metadata.uid):
            return None
        if req.status.phase == crr_api.PHASE_SUCCEEDED:
            self._delete(ns, name)
            return RestartOutcome.RESTARTED
        if req.status.phase == crr_api.PHASE_FAILED:
            self._delete(ns, name)
            return RestartOutcome.FAILED
        created = req.metadata.creation_timestamp
        if (created is not None
                and (utcnow() - created).total_seconds() >= self.wait_seconds):
            self._delete(ns, name)
            return RestartOutcome.FAILED
        return RestartOutcome.PENDING


def failover_recreate(cluster: InMemoryCluster, pod: Pod) -> bool:
    """Delete the failed pod; the engine's next reconcile recreates it
    (failover.go:149-172). Stamps the failover timestamp annotation first.
    Returns False if the pod was already gone (caller must drain any deletion
    expectation it raised)."""
    try:
        cluster.patch_meta(
            Pod, pod.metadata.namespace, pod.metadata.name,
            annotations={constants.ANNOTATION_LAST_FAILOVER_TIMESTAMP: utcnow().isoformat()},
            # The victim must actually go away: failover delete overrides the
            # preempt-protector (it is not a preemption).
            remove_finalizers=[constants.FINALIZER_PREEMPT_PROTECTOR],
        )
        cluster.delete(Pod, pod.metadata.namespace, pod.metadata.name)
        return True
    except NotFoundError:
        return False


def failover_inplace_restart(
    cluster: InMemoryCluster, pod: Pod, restarter: Optional[InPlaceRestarter]
) -> RestartOutcome:
    """In-place restart via the CRR seam, falling back to recreate
    (failover.go:210-264). RESTARTED = the pod was restarted in place;
    PENDING = a CRR is in flight, re-drive on a later reconcile pass;
    FAILED = the restart was impossible and a recreate happened instead.
    Legacy executors returning a bool are normalized (True→RESTARTED,
    False→FAILED)."""
    if restarter is None:
        failover_recreate(cluster, pod)
        return RestartOutcome.FAILED
    out = restarter.restart(cluster, pod)
    if isinstance(out, bool):
        out = RestartOutcome.RESTARTED if out else RestartOutcome.FAILED
    if out is RestartOutcome.FAILED:
        failover_recreate(cluster, pod)
    return out
