"""ModelVersion controller: trained artifact → OCI image pipeline.

Analog of /root/reference/controllers/model/modelversion_controller.go
(SURVEY §2.6). On a ModelVersion appearing (emitted by the job engine on
success — engine.py ``_ensure_model_version``):

1. ensure the owning ``Model`` exists and owns the version
   (modelversion_controller.go:114-163);
2. create the storage PV + PVC via the provider registry and bind them (the
   in-memory stand-in for the volume binder; reference waits on ClaimBound,
   :180-184);
3. create the ``dockerfile`` ConfigMap — the build recipe that COPYs the
   artifact directory into the image (:286-311);
4. launch the image-build pod (Kaniko in the reference, :318-406) mounting
   dockerfile + artifact volume + registry secret;
5. poll its phase → ``ImageBuildSucceeded``/``Failed`` (:252-267) and update
   ``Model.status.latest_version`` (:234-242).

TPU note: the default storage flavor for TPU-on-GKE artifacts is GCS
(``tpu_on_k8s.storage.GCSProvider``, new vs the reference's NFS/local pair);
checkpoints written by ``tpu_on_k8s.train`` land on the same volume the build
pod packages.
"""
from __future__ import annotations

from typing import Optional

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import (
    ConfigMap,
    Container,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
    PodSpec,
    Volume,
    VolumeMount,
    utcnow,
)
from tpu_on_k8s.api.model_types import (
    ImageBuildPhase,
    Model,
    ModelVersion,
)
from tpu_on_k8s.client.cluster import (
    AlreadyExistsError,
    InMemoryCluster,
    NotFoundError,
    WatchEvent,
)
from tpu_on_k8s.controller.config import JobControllerConfig
from tpu_on_k8s.controller.runtime import Controller, Manager, Request, Result
from tpu_on_k8s.storage import (
    PersistentVolume,
    PersistentVolumeClaim,
    PersistentVolumeClaimSpec,
    provider_for_storage,
)

LABEL_MODEL_VERSION = "model.distributed.tpu.io/model-version-name"
BUILDER_POD_SUFFIX = "-image-build"
DOCKERFILE = """FROM busybox:1.36
COPY build/ {model_path}
"""


class ModelVersionReconciler:
    def __init__(self, cluster: InMemoryCluster,
                 config: Optional[JobControllerConfig] = None) -> None:
        self.cluster = cluster
        self.config = config or JobControllerConfig()

    # ---------------------------------------------------------------- reconcile
    def reconcile(self, request: Request) -> Result:
        mv = self.cluster.try_get(ModelVersion, request.namespace, request.name)
        if mv is None:
            return Result()
        if mv.status.image_build_phase in (ImageBuildPhase.SUCCEEDED,
                                           ImageBuildPhase.FAILED):
            return Result()

        model = self._ensure_model(mv)
        provider = provider_for_storage(mv.spec.storage)
        if provider is None:
            return self._finish(mv, model, ImageBuildPhase.FAILED,
                                "no storage provider configured for model version")

        if not self._ensure_storage(mv, provider):
            return Result(requeue_after=1.0)  # claim not bound yet (:180-184)
        self._ensure_dockerfile(mv, provider)
        pod = self._ensure_build_pod(mv, provider)

        if pod.status.phase == PodPhase.SUCCEEDED:
            return self._finish(mv, model, ImageBuildPhase.SUCCEEDED, "image built")
        if pod.status.phase == PodPhase.FAILED:
            return self._finish(mv, model, ImageBuildPhase.FAILED,
                                pod.status.message or "image build pod failed")
        self._set_phase(mv, ImageBuildPhase.BUILDING, "image build in progress")
        return Result(requeue_after=self.config.sync_period_seconds)

    # ------------------------------------------------------------------- steps
    def _ensure_model(self, mv: ModelVersion) -> Model:
        """Ensure the named Model exists and owns this version
        (modelversion_controller.go:114-163)."""
        name = mv.spec.model_name or mv.metadata.name
        model = self.cluster.try_get(Model, mv.metadata.namespace, name)
        if model is None:
            model = Model(metadata=ObjectMeta(
                name=name, namespace=mv.metadata.namespace,
                labels={constants.LABEL_MODEL_NAME: name}))
            try:
                model = self.cluster.create(model)
            except AlreadyExistsError:
                model = self.cluster.get(Model, mv.metadata.namespace, name)
        if not any(r.uid == model.metadata.uid
                   for r in mv.metadata.owner_references):
            def mutate(v: ModelVersion) -> None:
                if not any(r.uid == model.metadata.uid
                           for r in v.metadata.owner_references):
                    v.metadata.owner_references.append(OwnerReference(
                        api_version=model.api_version, kind=model.kind,
                        name=model.metadata.name, uid=model.metadata.uid))
            try:
                self.cluster.update_with_retry(
                    ModelVersion, mv.metadata.namespace, mv.metadata.name, mutate)
            except NotFoundError:
                pass
        return model

    def _pv_name(self, mv: ModelVersion) -> str:
        """Local storage pins one PV per node (reference per-node names,
        :412-518); other flavors share one."""
        ls = mv.spec.storage.local_storage
        if ls is not None and ls.node_name:
            return f"mv-pv-{mv.metadata.name}-{ls.node_name}"
        return f"mv-pv-{mv.metadata.name}"

    def _ensure_storage(self, mv: ModelVersion, provider) -> bool:
        """PV + PVC + bind. Returns True once the claim is Bound. The bind
        step stands in for kube-controller-manager's volume binder."""
        pv_name = self._pv_name(mv)
        pv = self.cluster.try_get(PersistentVolume, "", pv_name)
        if pv is None:
            pv = provider.create_persistent_volume(mv, pv_name)
            pv.metadata.namespace = ""
            try:
                self.cluster.create(pv)
            except AlreadyExistsError:
                pass
        pvc = self.cluster.try_get(PersistentVolumeClaim, mv.metadata.namespace, pv_name)
        if pvc is None:
            pvc = PersistentVolumeClaim(
                metadata=ObjectMeta(
                    name=pv_name, namespace=mv.metadata.namespace,
                    labels={LABEL_MODEL_VERSION: mv.metadata.name},
                    owner_references=[self._owner_ref(mv)]),
                spec=PersistentVolumeClaimSpec(volume_name=pv_name))
            try:
                pvc = self.cluster.create(pvc)
            except AlreadyExistsError:
                pvc = self.cluster.get(PersistentVolumeClaim, mv.metadata.namespace, pv_name)
        if pvc.status.phase != "Bound":
            def mutate(c: PersistentVolumeClaim) -> None:
                c.status.phase = "Bound"
            try:
                self.cluster.update_with_retry(
                    PersistentVolumeClaim, mv.metadata.namespace, pv_name,
                    mutate, subresource="status")
            except NotFoundError:
                return False
        return True

    @staticmethod
    def _dockerfile_name(mv: ModelVersion) -> str:
        return f"{mv.metadata.name}-dockerfile"

    def _ensure_dockerfile(self, mv: ModelVersion, provider) -> None:
        name = self._dockerfile_name(mv)
        if self.cluster.try_get(ConfigMap, mv.metadata.namespace, name) is not None:
            return
        cm = ConfigMap(
            metadata=ObjectMeta(
                name=name, namespace=mv.metadata.namespace,
                labels={LABEL_MODEL_VERSION: mv.metadata.name},
                owner_references=[self._owner_ref(mv)]),
            data={"dockerfile": DOCKERFILE.format(
                model_path=provider.get_model_mount_path(mv))})
        try:
            self.cluster.create(cm)
        except AlreadyExistsError:
            pass

    def _ensure_build_pod(self, mv: ModelVersion, provider) -> Pod:
        """The Kaniko-pod analog (:318-406): builder image + dockerfile +
        artifact volume + registry secret, node-pinned for local storage."""
        name = f"{mv.metadata.name}{BUILDER_POD_SUFFIX}"
        pod = self.cluster.try_get(Pod, mv.metadata.namespace, name)
        if pod is not None:
            return pod
        image = self._image_ref(mv)
        spec = PodSpec(
            restart_policy="Never",
            containers=[Container(
                name="image-builder",
                image=self.config.model_image_builder,
                args=[f"--dockerfile=/workspace/dockerfile",
                      f"--context=dir:///workspace",
                      f"--destination={image}"],
                volume_mounts=[
                    # ConfigMap materializes one file per key under the mount:
                    # key "dockerfile" → /workspace/dockerfile (:391-394).
                    VolumeMount(name="dockerfile", mount_path="/workspace"),
                    # The artifact PVC is the build context's COPY source
                    # (:363-390).
                    VolumeMount(name="artifact", mount_path="/workspace/build"),
                    VolumeMount(name="regcred",
                                mount_path="/kaniko/.docker", read_only=True),
                ])],
            volumes=[
                Volume(name="dockerfile",
                       config_map_name=self._dockerfile_name(mv)),
                # Kaniko reads /kaniko/.docker/config.json; the dockerconfig
                # secret key must be projected to that filename (:348-356).
                Volume(name="regcred", secret_name=constants.REGISTRY_SECRET_NAME,
                       items={".dockerconfigjson": "config.json"}),
                Volume(name="artifact", pvc_claim_name=self._pv_name(mv)),
            ])
        ls = mv.spec.storage.local_storage
        if ls is not None and ls.node_name:
            # Local artifacts only exist on the training node: pin the build
            # there (reference node-pinned Kaniko pod, :318-406).
            spec.node_name = ls.node_name
        pod = Pod(
            metadata=ObjectMeta(
                name=name, namespace=mv.metadata.namespace,
                labels={LABEL_MODEL_VERSION: mv.metadata.name},
                owner_references=[self._owner_ref(mv)]),
            spec=spec)
        try:
            return self.cluster.create(pod)
        except AlreadyExistsError:
            return self.cluster.get(Pod, mv.metadata.namespace, name)

    # ------------------------------------------------------------------ status
    def _image_ref(self, mv: ModelVersion) -> str:
        tag = mv.spec.image_tag or mv.metadata.name
        repo = mv.spec.image_repo or f"registry.local/{mv.spec.model_name or mv.metadata.name}"
        return f"{repo}:{tag}"

    def _set_phase(self, mv: ModelVersion, phase: ImageBuildPhase, message: str) -> None:
        if mv.status.image_build_phase == phase and mv.status.message == message:
            return

        def mutate(v: ModelVersion) -> None:
            v.status.image_build_phase = phase
            v.status.message = message
            if phase in (ImageBuildPhase.SUCCEEDED, ImageBuildPhase.FAILED):
                v.status.finish_time = v.status.finish_time or utcnow()
                if phase == ImageBuildPhase.SUCCEEDED:
                    v.status.image = self._image_ref(v)
        try:
            self.cluster.update_with_retry(
                ModelVersion, mv.metadata.namespace, mv.metadata.name, mutate,
                subresource="status")
        except NotFoundError:
            pass

    def _finish(self, mv: ModelVersion, model: Model,
                phase: ImageBuildPhase, message: str) -> Result:
        self._set_phase(mv, phase, message)
        if phase == ImageBuildPhase.SUCCEEDED:
            def mutate(m: Model) -> None:
                m.status.latest_version_name = mv.metadata.name
                m.status.latest_image = self._image_ref(mv)
            try:
                self.cluster.update_with_retry(
                    Model, model.metadata.namespace, model.metadata.name, mutate,
                    subresource="status")
            except NotFoundError:
                pass
        self.cluster.record_event(
            mv, "Normal" if phase == ImageBuildPhase.SUCCEEDED else "Warning",
            str(phase.value), message)
        return Result()

    def _owner_ref(self, mv: ModelVersion) -> OwnerReference:
        return OwnerReference(
            api_version=mv.api_version, kind=mv.kind, name=mv.metadata.name,
            uid=mv.metadata.uid, controller=True)


def setup_modelversion_controller(
    cluster: InMemoryCluster,
    manager: Manager,
    config: Optional[JobControllerConfig] = None,
) -> ModelVersionReconciler:
    """Wire the controller: watch ModelVersions + their build pods
    (reference SetupWithManager, modelversion_controller.go:45-67)."""
    reconciler = ModelVersionReconciler(cluster, config=config)
    controller = Controller("modelversion", reconciler.reconcile)
    manager.add_controller(controller)

    def on_event(event: WatchEvent) -> None:
        if event.kind == constants.KIND_MODELVERSION:
            controller.enqueue(event.obj.metadata.namespace, event.obj.metadata.name)
        elif event.kind == "Pod":
            owner = event.obj.metadata.labels.get(LABEL_MODEL_VERSION)
            if owner:
                controller.enqueue(event.obj.metadata.namespace, owner)

    cluster.watch(on_event)
    return reconciler
