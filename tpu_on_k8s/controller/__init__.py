"""Controller plane (L3/L5): generic job engine + concrete reconcilers.

Analog of /root/reference/controllers/ — the shared ``JobEngine``
(controllers/common/) and the TPUJob / ModelVersion / elastic reconcilers.
"""
