"""The generic job engine: ReconcileJobs / ReconcilePods / ReconcileServices.

Analog of /root/reference/controllers/common/{job,pod,service}.go — the shared
reconcile algorithm a concrete workload reconciler (``tpu_on_k8s.controller.
tpujob``) plugs into via ``WorkloadHooks`` (the ControllerInterface contract,
interface.go:28-97).

Reconcile flow (job.go:55-342):
  termination checks (backoff limit, active deadline, finished → cleanup + TTL +
  ModelVersion emit) → gang podgroup creation → elastic checkpoint/scale gate →
  model-path env injection → per-task DAG-gated pod+service reconciliation →
  status FSM update (conflict-retried).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Protocol, Tuple

from tpu_on_k8s import chaos
from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import (
    EnvVar,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
    Service,
    ServicePort,
    ServiceSpec,
    Volume,
    VolumeMount,
    utcnow,
)
from tpu_on_k8s.api.model_types import ModelVersion
from tpu_on_k8s.api.types import (
    CleanPodPolicy,
    JobConditionType,
    ReplicaStatus,
    RestartPolicy,
    TaskSpec,
    TaskType,
    TPUJob,
)
from tpu_on_k8s.client.cluster import (
    AlreadyExistsError,
    InMemoryCluster,
    NotFoundError,
)
from tpu_on_k8s.controller import dag, failover, hostnetwork
from tpu_on_k8s.controller.config import JobControllerConfig
from tpu_on_k8s.controller.expectations import Expectations, expectation_key
from tpu_on_k8s.controller.runtime import Request, Result
from tpu_on_k8s.features import FeatureGates
from tpu_on_k8s.metrics import JobMetrics
from tpu_on_k8s.utils import conditions, serde


class GangSchedulerProtocol(Protocol):
    """Gang scheduler seam (reference pkg/gangscheduler/interface.go:31-48)."""

    def name(self) -> str: ...
    def create_podgroups(self, job: TPUJob) -> None: ...
    def bind_pod(self, job: TPUJob, pod: Pod, task_type: TaskType) -> None: ...
    def delete_podgroups(self, job: TPUJob) -> None: ...


class WorkloadHooks(Protocol):
    """What a concrete workload reconciler supplies to the engine
    (ControllerInterface, interface.go:28-79)."""

    def task_order(self, job: TPUJob) -> List[TaskType]: ...
    def is_master(self, task_type: TaskType, index: int) -> bool: ...
    def needs_service(self, job: TPUJob, task_type: TaskType) -> bool: ...
    def set_cluster_spec(self, job: TPUJob, pod: Pod, task_type: TaskType, index: int) -> None: ...
    def update_job_status(self, job: TPUJob, pods_by_type: Dict[TaskType, List[Pod]]) -> None: ...
    def failover_action(self, job: TPUJob, pod: Pod) -> str: ...  # "recreate"|"inplace"
    def enable_elastic_scaling(self, job: TPUJob) -> bool: ...


@dataclass
class _LaunchMeter:
    first_observed: bool = False
    all_observed: bool = False


class JobEngine:
    """Shared engine embedded by concrete reconcilers
    (reference JobController struct, controllers/common/controller.go:81-119)."""

    def __init__(
        self,
        cluster: InMemoryCluster,
        hooks: WorkloadHooks,
        config: Optional[JobControllerConfig] = None,
        gang_scheduler: Optional[GangSchedulerProtocol] = None,
        restarter: Optional[failover.InPlaceRestarter] = None,
        metrics: Optional[JobMetrics] = None,
        gates: Optional[FeatureGates] = None,
        elastic_controller=None,  # set by controller.elastic when enabled
    ) -> None:
        self.cluster = cluster
        self.hooks = hooks
        self.config = config or JobControllerConfig()
        self.gang = gang_scheduler
        self.restarter = restarter
        self.metrics = metrics or JobMetrics()
        self.gates = gates or FeatureGates()
        self.elastic = elastic_controller
        self.expectations = Expectations(self.config.expectation_ttl_seconds)
        self._lock = threading.Lock()
        # In-memory failover counters feeding the backoff-limit termination
        # check (the reference derives this from its BackoffStatesQueue +
        # container restart counts, job.go:385-419).
        self._failover_counts: Dict[str, int] = {}
        self._launch_meters: Dict[str, _LaunchMeter] = {}
        # In-flight level-triggered CRR restarts: (ns, pod) → job_key. Keys
        # are re-driven by _collect_slice_restarts each pass until the CRR
        # settles — O(active restarts) GETs, never a collection LIST. Lost
        # on operator restart, like the reference's in-memory expectations;
        # the node agent's TTL reaper then clears any orphaned CRR.
        self._inflight_inplace: Dict[Tuple[str, str], str] = {}
        self.port_allocator = hostnetwork.PortAllocator(
            self.config.hostnetwork_port_range)

    # ------------------------------------------------------------------ helpers
    @staticmethod
    def job_key(job: TPUJob) -> str:
        return f"{job.metadata.namespace}/{job.metadata.name}"

    def job_selector(self, job: TPUJob) -> Dict[str, str]:
        return {constants.LABEL_JOB_NAME: job.metadata.name}

    def task_labels(self, job: TPUJob, task_type: TaskType, index: int) -> Dict[str, str]:
        """Reference GenerateLabels (controller.go:141-151) — with its nil-map
        panic fixed by construction."""
        return {
            constants.LABEL_JOB_NAME: job.metadata.name,
            constants.LABEL_GROUP_NAME: constants.API_GROUP,
            constants.LABEL_TASK_TYPE: task_type.value.lower(),
            constants.LABEL_TASK_INDEX: str(index),
        }

    def owner_ref(self, job: TPUJob) -> OwnerReference:
        return OwnerReference(
            api_version=job.api_version,
            kind=job.kind,
            name=job.metadata.name,
            uid=job.metadata.uid,
            controller=True,
            block_owner_deletion=True,
        )

    def record_failover(self, job: TPUJob) -> int:
        with self._lock:
            key = self.job_key(job)
            self._failover_counts[key] = self._failover_counts.get(key, 0) + 1
            return self._failover_counts[key]

    def restart_count(self, job: TPUJob, pods: List[Pod]) -> int:
        """Failure-attributable restarts feeding the backoff-limit check.
        Healthy elastic-rescale restarts are excluded (they bump container
        restart counts too, but a successful scale event must never fail the
        job as BackoffLimitExceeded)."""
        with self._lock:
            n = self._failover_counts.get(self.job_key(job), 0)
        for pod in pods:
            for cs in pod.status.container_statuses:
                n += cs.restart_count
            try:
                n -= int(pod.metadata.annotations.get(
                    constants.ANNOTATION_ELASTIC_RESTARTS, "0"))
            except ValueError:
                pass
        return max(n, 0)

    def forget_job(self, key: str) -> None:
        with self._lock:
            self._failover_counts.pop(key, None)
            self._launch_meters.pop(key, None)
        self.expectations.delete_expectations(key)

    # ---------------------------------------------------------------- reconcile
    def reconcile(self, request: Request) -> Result:
        job = self.cluster.try_get(TPUJob, request.namespace, request.name)
        if job is None:
            self.forget_job(f"{request.namespace}/{request.name}")
            return Result()

        if job.metadata.deletion_timestamp is not None:
            # Job is being deleted: release preempt-protector finalizers so
            # cascade GC can finish (reference cleanUpPreemptFinalizers,
            # torchjob_controller.go:480-505).
            self._cleanup_preempt_finalizers(job)
            return Result()

        key = self.job_key(job)
        fault = chaos.fire(chaos.SITE_RECONCILE, job=key)
        if fault is not None:
            # injected BEFORE expectations/pod listing so the very pass that
            # carries the fault also observes and classifies it — the same
            # ordering a kubelet status write racing a reconcile produces
            self._apply_chaos_fault(job, fault)
        if not self._expectations_satisfied(job):
            return Result(requeue_after=self.config.sync_period_seconds)

        pods = self._get_pods_for_job(job)
        services = self._get_services_for_job(job)
        pods_by_type = self._slice_by_type(pods)

        # --- termination path (job.go:105-200) --------------------------------
        if conditions.is_finished(job.status):
            return self._finish_cleanup(job, pods, services)

        try:
            # Reject un-schedulable slice shapes up front: letting an unknown
            # accelerator/topology reach set_cluster_spec would crash-loop the
            # reconciler behind raised expectations.
            from tpu_on_k8s.gang import topology as tpu_topology

            tpu_topology.validate_slice(job.spec.tpu_policy.accelerator,
                                        job.spec.tpu_policy.topology)
            if self.gang is not None and self.config.enable_gang_scheduling:
                # A worker group smaller than the slice quorum could never be
                # gang-admitted — fail loudly instead of pending forever.
                from tpu_on_k8s.gang.scheduler import validate_gang_feasibility

                validate_gang_feasibility(job)
        except (KeyError, ValueError) as e:
            return self._fail_job(job, pods, services, "InvalidTPUPolicy", str(e))

        backoff_limit = job.spec.run_policy.backoff_limit
        if backoff_limit is not None and self.restart_count(job, pods) > backoff_limit:
            return self._fail_job(job, pods, services, "BackoffLimitExceeded",
                                  f"restart count exceeded backoff limit {backoff_limit}")
        if self._past_active_deadline(job):
            return self._fail_job(job, pods, services, "DeadlineExceeded",
                                  "job active deadline exceeded")

        # --- running path -----------------------------------------------------
        if self.gang is not None and self.config.enable_gang_scheduling:
            self.gang.create_podgroups(job)

        if self.elastic is not None and self.hooks.enable_elastic_scaling(job):
            # Checkpoint-gated generation scaling (job.go:225-248, SURVEY §3.3).
            requeue = self.elastic.reconcile(job, pods)
            if requeue is not None:
                return requeue

        self._inject_model_path(job)

        ctx: Dict[str, object] = {}
        for task_type in self.hooks.task_order(job):
            task = job.spec.tasks.get(task_type)
            if task is None:
                continue
            if self.gates.enabled("DAGScheduling") and not dag.dag_conditions_ready(
                job, task_type, pods_by_type
            ):
                continue
            self.reconcile_pods(job, task_type, task, pods_by_type.get(task_type, []), ctx)
            if self.hooks.needs_service(job, task_type):
                self.reconcile_services(job, task_type, task, services, ctx)

        self._update_status(job, pods_by_type)
        self._meter_launch_delays(job, pods)
        return Result(requeue_after=self.config.sync_period_seconds)

    # ------------------------------------------------------------ pods/services
    def _get_pods_for_job(self, job: TPUJob) -> List[Pod]:
        """Label-select + adopt orphans (reference AdoptAndClaimPods,
        pod.go:717-745)."""
        pods = self.cluster.list(Pod, job.metadata.namespace, self.job_selector(job))
        claimed = []
        for pod in pods:
            ref = pod.metadata.controller_ref()
            if ref is None:
                try:
                    pod = self.cluster.update_with_retry(
                        Pod, pod.metadata.namespace, pod.metadata.name,
                        lambda p: p.metadata.owner_references.append(self.owner_ref(job)))
                except NotFoundError:
                    continue
            elif ref.uid != job.metadata.uid:
                continue  # owned by someone else
            claimed.append(pod)
        return claimed

    def _get_services_for_job(self, job: TPUJob) -> List[Service]:
        svcs = self.cluster.list(Service, job.metadata.namespace, self.job_selector(job))
        out = []
        for svc in svcs:
            ref = svc.metadata.controller_ref()
            if ref is None:
                try:
                    svc = self.cluster.update_with_retry(
                        Service, svc.metadata.namespace, svc.metadata.name,
                        lambda s: s.metadata.owner_references.append(self.owner_ref(job)))
                except NotFoundError:
                    continue
            elif ref.uid != job.metadata.uid:
                continue
            out.append(svc)
        return out

    @staticmethod
    def _slice_by_type(pods: List[Pod]) -> Dict[TaskType, List[Pod]]:
        by_type: Dict[TaskType, List[Pod]] = {}
        for pod in pods:
            raw = pod.metadata.labels.get(constants.LABEL_TASK_TYPE, "")
            try:
                tt = TaskType.normalize(raw)
            except ValueError:
                continue
            by_type.setdefault(tt, []).append(pod)
        return by_type

    @staticmethod
    def pod_index(pod: Pod) -> int:
        try:
            return int(pod.metadata.labels.get(constants.LABEL_TASK_INDEX, "-1"))
        except ValueError:
            return -1

    def reconcile_pods(
        self,
        job: TPUJob,
        task_type: TaskType,
        task: TaskSpec,
        existing: List[Pod],
        ctx: Dict[str, object],
    ) -> None:
        """Reference ReconcilePods (pod.go:361-687): create missing indices,
        delete out-of-range, classify failures."""
        by_index: Dict[int, List[Pod]] = {}
        for pod in existing:
            by_index.setdefault(self.pod_index(pod), []).append(pod)

        self._collect_slice_restarts(job)
        exp_key = expectation_key(self.job_key(job), task_type.value, "pods")
        to_create = [i for i in range(task.num_tasks) if not by_index.get(i)]
        if to_create:
            self.expectations.expect_creations(exp_key, len(to_create))
            for i in to_create:
                self._create_new_pod(job, task_type, task, i, ctx)

        for index, pods in by_index.items():
            for pod in pods:
                if index < 0 or index >= task.num_tasks:
                    self._delete_pod(job, pod, exp_key)
                    continue
                self._reconcile_one_pod(job, task_type, task, pod, exp_key)

    def _create_new_pod(
        self, job: TPUJob, task_type: TaskType, task: TaskSpec, index: int,
        ctx: Dict[str, object],
    ) -> None:
        """Reference createNewPod (pod.go:503-637)."""
        name = conditions.gen_general_name(job.metadata.name, task_type, index)
        pod = Pod(
            metadata=ObjectMeta(
                name=name,
                namespace=job.metadata.namespace,
                labels={**task.template.metadata.labels,
                        **self.task_labels(job, task_type, index)},
                annotations=dict(task.template.metadata.annotations),
                owner_references=[self.owner_ref(job)],
            ),
            spec=serde.deep_copy(task.template.spec),
        )
        elastic = self.hooks.enable_elastic_scaling(job)
        if elastic:
            # Generation label + preempt-protector finalizer (pod.go:525-528).
            pod.metadata.labels[constants.LABEL_JOB_GENERATION] = str(job.metadata.generation)
            pod.metadata.finalizers.append(constants.FINALIZER_PREEMPT_PROTECTOR)

        if hostnetwork.enabled(job.metadata.annotations):
            ports: hostnetwork.PortMap = ctx.setdefault(constants.CONTEXT_HOSTNETWORK_PORTS, {})  # type: ignore[assignment]
            port = self.port_allocator.allocate(
                f"{job.metadata.namespace}/{name}")
            ports[name] = port
            hostnetwork.setup_pod_hostnetwork(pod, port)

        # Restart-policy mapping: OnExitCode is controller-managed, so the pod
        # itself never restarts (pod.go:556-561).
        policy = task.restart_policy or RestartPolicy.NEVER
        pod.spec.restart_policy = (
            "Never" if policy == RestartPolicy.ON_EXIT_CODE else policy.value
        )

        self.hooks.set_cluster_spec(job, pod, task_type, index)

        if self.gang is not None and self.config.enable_gang_scheduling:
            self.gang.bind_pod(job, pod, task_type)

        spot = task.spot_task_spec
        if spot and spot.num_spot_tasks > 0 and index >= task.num_tasks - spot.num_spot_tasks:
            # Trailing replicas run at spot priority (pod.go:592-603).
            if spot.priority_class_name:
                pod.spec.priority_class_name = spot.priority_class_name
            pod.metadata.labels[constants.LABEL_SPOT_TASK] = "true"
            pod.metadata.labels.update(spot.labels)

        try:
            self.cluster.create(pod)
            self.cluster.record_event(job, "Normal", "SuccessfulCreatePod", f"Created pod {pod.metadata.name}")
        except AlreadyExistsError:
            exp_key = expectation_key(self.job_key(job), task_type.value, "pods")
            self.expectations.creation_observed(exp_key)

    def _delete_pod(self, job: TPUJob, pod: Pod, exp_key: str) -> None:
        self.expectations.expect_deletions(exp_key, 1)
        try:
            self.cluster.patch_meta(
                Pod, pod.metadata.namespace, pod.metadata.name,
                remove_finalizers=[constants.FINALIZER_PREEMPT_PROTECTOR])
            self.cluster.delete(Pod, pod.metadata.namespace, pod.metadata.name)
            self.cluster.record_event(job, "Normal", "SuccessfulDeletePod", f"Deleted pod {pod.metadata.name}")
        except NotFoundError:
            self.expectations.deletion_observed(exp_key)

    def _reconcile_one_pod(
        self, job: TPUJob, task_type: TaskType, task: TaskSpec, pod: Pod, exp_key: str
    ) -> None:
        """Reference reconcileOnePod (pod.go:640-687): failed pods either fail
        over (recreate / in-place restart) or stand as permanent failures for
        the status FSM to judge."""
        if pod.status.phase != PodPhase.FAILED:
            return
        policy = task.restart_policy or RestartPolicy.NEVER
        if not failover.should_pod_failover(pod, policy):
            return
        if not conditions.has_condition(job.status, JobConditionType.RESTARTING):
            # Stamp once per failover episode: re-stamping each pod/pass
            # would churn the condition's message+timestamp and turn the
            # level-triggered pending protocol into a status-write busy loop.
            conditions.update_job_conditions(
                job.status, JobConditionType.RESTARTING, "PodFailover",
                f"pod {pod.metadata.name} failed (exit {failover.pod_exit_code(pod)}, "
                f"reason {pod.status.reason or 'n/a'}); restarting")
        if self.hooks.failover_action(job, pod) == "inplace":
            outcome = failover.failover_inplace_restart(
                self.cluster, pod, self.restarter)
            # The slice restarts TOGETHER: siblings' CRRs are posted on the
            # same pass as the failed pod's (not after it recovers), so the
            # whole slice re-enters rendezvous at once. Re-driven every pass
            # while pending — a no-op once each sibling settled.
            self._failover_slice_siblings(job, task_type, pod)
            key = (pod.metadata.namespace, pod.metadata.name)
            if outcome is failover.RestartOutcome.PENDING:
                # CRR in flight; the protocol advances level-triggered across
                # reconciles (reference failover.go is level-triggered the
                # same way) — the pass NEVER blocks on a node agent. Track it
                # so _collect_slice_restarts settles the CRR even if the pod
                # recovers before this path sees the Succeeded phase.
                self._inflight_inplace[key] = self.job_key(job)
                return
            self._inflight_inplace.pop(key, None)
            self.metrics.restarted()
            if outcome is failover.RestartOutcome.RESTARTED:
                # In-place restarts surface in container restart_count, which
                # restart_count() already sums — recording a failover too would
                # double-count toward the backoff limit.
                return
            self.record_failover(job)  # fell back to delete+recreate
            return
        self.metrics.restarted()
        self.record_failover(job)
        self.expectations.expect_deletions(exp_key, 1)
        if not failover.failover_recreate(self.cluster, pod):
            # Pod vanished under us: drain the expectation we just raised
            # or the job wedges until the expectation TTL.
            self.expectations.deletion_observed(exp_key)
        self._failover_slice_siblings(job, task_type, pod)

    def _failover_slice_siblings(self, job: TPUJob, task_type: TaskType,
                                 failed: Pod) -> None:
        """Slice-atomic failover (SURVEY §5.3 TPU note): a TPU slice runs one
        SPMD program, so one dead host kills every host's step loop in that
        slice — in-place-restart the slice's surviving workers so they
        re-enter rendezvous together instead of hanging on a dead collective.
        The reference restarts only the failed pod (its DDP ranks were
        independent processes); on TPU the slice is the failure domain."""
        from tpu_on_k8s.gang import topology as tpu_topology

        if not self.config.slice_atomic_failover:
            return
        if task_type is not TaskType.WORKER:
            return
        tpu = job.spec.tpu_policy
        try:
            hosts_per = tpu_topology.hosts_per_slice(tpu.accelerator, tpu.topology)
        except (KeyError, ValueError):
            return
        if hosts_per <= 1:
            return
        try:
            failed_idx = int(failed.metadata.labels.get(
                constants.LABEL_TASK_INDEX, "-1"))
        except ValueError:
            return
        if failed_idx < 0:
            return
        slice_id = failed_idx // hosts_per
        selector = {constants.LABEL_JOB_NAME: job.metadata.name,
                    constants.LABEL_TASK_TYPE: TaskType.WORKER.value.lower()}
        # (uid, restart epoch) identifies the failover incident — uid alone
        # would miss a second failure of the same in-place-restarted pod.
        # Each sibling is restarted AT MOST ONCE per incident: the annotation
        # marker (stable across passes — the failed pod's status is frozen
        # while it stays Failed) records the incident a sibling was last
        # restarted for, and in-flight sibling CRRs are only COLLECTED by
        # ``_collect_slice_restarts`` on later passes — never re-posted,
        # which would loop restarts while the primary is pending.
        epoch = sum(cs.restart_count for cs in failed.status.container_statuses)
        incident = f"{failed.metadata.uid}:{epoch}"
        initiated = 0
        for sibling in self.cluster.list(Pod, job.metadata.namespace, selector):
            if sibling.metadata.name == failed.metadata.name:
                continue
            try:
                idx = int(sibling.metadata.labels.get(
                    constants.LABEL_TASK_INDEX, "-1"))
            except ValueError:
                continue
            if idx // hosts_per != slice_id:
                continue
            if sibling.status.phase != PodPhase.RUNNING:
                continue
            if sibling.metadata.annotations.get(
                    constants.ANNOTATION_SLICE_RESTART_FOR) == incident:
                continue  # already restarted (or restarting) for this one
            initiated += 1
            out = failover.failover_inplace_restart(self.cluster, sibling,
                                                    self.restarter)
            skey = (sibling.metadata.namespace, sibling.metadata.name)
            if out is failover.RestartOutcome.PENDING:
                self._inflight_inplace[skey] = self.job_key(job)
            elif out is failover.RestartOutcome.RESTARTED:
                self.metrics.restarted()
            else:
                self.record_failover(job)  # recreated by the fallback
            try:
                # Stamp AFTER initiating, so a crash in between re-initiates
                # (restart() adopts the already-posted CRR — no duplicate)
                # instead of leaving a never-restarted sibling behind.
                self.cluster.patch_meta(
                    Pod, sibling.metadata.namespace, sibling.metadata.name,
                    annotations={
                        constants.ANNOTATION_SLICE_RESTART_FOR: incident})
            except NotFoundError:
                pass
        if initiated:
            self.cluster.record_event(
                job, "Normal", "SliceFailover",
                f"slice {slice_id}: restarting {initiated} surviving host(s) "
                f"after {failed.metadata.name} failed")

    def _apply_chaos_fault(self, job: TPUJob, fault) -> None:
        """Materialize an injected ``PodFail`` / ``SlicePreempt`` as the pod
        status a kubelet would report (phase Failed, terminated exit code,
        kill reason), so the ordinary failover classification path — not a
        test backdoor — performs the recovery. Unknown fault types are
        ignored: a schedule aimed at another layer must not break reconciles."""
        from tpu_on_k8s.chaos import faults as chaos_faults
        from tpu_on_k8s.client.testing import KubeletSim  # the kubelet seam

        sim = KubeletSim(self.cluster)
        if isinstance(fault, chaos_faults.PodFail):
            try:
                tt = TaskType.normalize(fault.task_type)
            except ValueError:
                return
            name = conditions.gen_general_name(job.metadata.name, tt,
                                               fault.index)
            try:
                sim.terminate_pod(job.metadata.namespace, name,
                                  fault.exit_code, reason=fault.reason,
                                  phase=PodPhase.FAILED)
            except NotFoundError:
                pass
            return
        if isinstance(fault, chaos_faults.SlicePreempt):
            from tpu_on_k8s.gang import topology as tpu_topology

            tpu = job.spec.tpu_policy
            try:
                hosts_per = tpu_topology.hosts_per_slice(tpu.accelerator,
                                                         tpu.topology)
            except (KeyError, ValueError):
                hosts_per = 1
            selector = {constants.LABEL_JOB_NAME: job.metadata.name,
                        constants.LABEL_TASK_TYPE:
                            TaskType.WORKER.value.lower()}
            for pod in self.cluster.list(Pod, job.metadata.namespace,
                                         selector):
                idx = self.pod_index(pod)
                if idx < 0 or idx // hosts_per != fault.slice_index:
                    continue
                if pod.status.phase not in (PodPhase.PENDING,
                                            PodPhase.RUNNING):
                    continue
                try:
                    sim.terminate_pod(pod.metadata.namespace,
                                      pod.metadata.name, fault.exit_code,
                                      reason=fault.reason,
                                      phase=PodPhase.FAILED)
                except NotFoundError:
                    pass

    def _collect_slice_restarts(self, job: TPUJob) -> None:
        """Settle the job's in-flight CRRs: both fire-and-forget slice-
        sibling restarts and a primary pod whose in-place restart completed
        after the engine's last look at it (the pod is Running, so the
        failed-pod path no longer drives its protocol). Iterates the TRACKED
        keys only — O(active restarts) GETs per pass, never a collection
        LIST. Observe-only (never posts); a restart that settled FAILED
        falls back to recreate so a dead-runtime sibling can't keep running
        against a re-rendezvoused slice."""
        collect = getattr(self.restarter, "collect", None)
        if collect is None:
            return
        jkey = self.job_key(job)
        for key, owner in list(self._inflight_inplace.items()):
            if owner != jkey:
                continue
            pod = self.cluster.try_get(Pod, key[0], key[1])
            if pod is None:
                self._inflight_inplace.pop(key, None)
                continue
            if pod.status.phase == PodPhase.FAILED:
                # The failed-pod reconcile path owns it again (a fresh
                # failover episode); that path re-tracks on PENDING.
                self._inflight_inplace.pop(key, None)
                continue
            out = collect(pod)  # uid-checked inside; deletes when settled
            if out is failover.RestartOutcome.PENDING:
                continue
            self._inflight_inplace.pop(key, None)
            if out is failover.RestartOutcome.RESTARTED:
                self.metrics.restarted()
            elif out is failover.RestartOutcome.FAILED:
                # Runtime failure / deadline after the pod left the failed
                # path (slice sibling, or a recovered-then-wedged primary):
                # recreate so the slice re-enters rendezvous together.
                self.record_failover(job)
                failover.failover_recreate(self.cluster, pod)

    def reconcile_services(
        self,
        job: TPUJob,
        task_type: TaskType,
        task: TaskSpec,
        existing: List[Service],
        ctx: Dict[str, object],
    ) -> None:
        """Reference ReconcileServices (service.go:251-308): one headless service
        per task replica (name == pod name) so every host has stable DNS; in
        hostnetwork mode the target port is patched to the allocated host port
        (service.go:288-303)."""
        mine = [s for s in existing
                if s.metadata.labels.get(constants.LABEL_TASK_TYPE) == task_type.value.lower()]
        have = {s.metadata.name for s in mine}
        port = task.template.spec.coordinator_port()
        ports_ctx: hostnetwork.PortMap = ctx.get(constants.CONTEXT_HOSTNETWORK_PORTS, {})  # type: ignore[assignment]
        exp_key = expectation_key(self.job_key(job), task_type.value, "services")

        # Scale-down: prune services whose replica index no longer exists
        # (the pods reconciler does the same for pods).
        valid = {conditions.gen_general_name(job.metadata.name, task_type, i)
                 for i in range(task.num_tasks)}
        for svc in mine:
            if svc.metadata.name not in valid:
                try:
                    self.cluster.delete(Service, svc.metadata.namespace, svc.metadata.name)
                except NotFoundError:
                    pass

        by_name = {s.metadata.name: s for s in mine}
        for index in range(task.num_tasks):
            name = conditions.gen_general_name(job.metadata.name, task_type, index)
            target = ports_ctx.get(name) or self._live_pod_port(job, name) or port
            svc = by_name.get(name)
            if svc is not None:
                current = next((p.target_port for p in svc.spec.ports
                                if p.name == constants.DEFAULT_PORT_NAME), None)
                if current is not None and current != target:
                    self._patch_service_target_port(job, name, target)
                continue
            svc = Service(
                metadata=ObjectMeta(
                    name=name,
                    namespace=job.metadata.namespace,
                    labels=self.task_labels(job, task_type, index),
                    owner_references=[self.owner_ref(job)],
                ),
                spec=ServiceSpec(
                    cluster_ip="None",
                    selector=self.task_labels(job, task_type, index),
                    ports=[ServicePort(name=constants.DEFAULT_PORT_NAME, port=port,
                                       target_port=target)],
                ),
            )
            self.expectations.expect_creations(exp_key, 1)
            try:
                self.cluster.create(svc)
            except AlreadyExistsError:
                self.expectations.creation_observed(exp_key)

    def _patch_service_target_port(self, job: TPUJob, name: str, target: int) -> None:
        def mutate(svc: Service) -> None:
            for p in svc.spec.ports:
                if p.name == constants.DEFAULT_PORT_NAME:
                    p.target_port = target

        try:
            self.cluster.update_with_retry(Service, job.metadata.namespace, name, mutate)
        except NotFoundError:
            pass

    def _live_pod_port(self, job: TPUJob, pod_name: str) -> Optional[int]:
        """Actual coordinator port of a live pod — for hostnetwork pods this is
        the allocated host port, which survives in the pod spec while the
        per-reconcile port context does not."""
        pod = self.cluster.try_get(Pod, job.metadata.namespace, pod_name)
        if pod is None:
            return None
        return pod.spec.coordinator_port()

    # ------------------------------------------------------------------- status
    def _update_status(self, job: TPUJob, pods_by_type: Dict[TaskType, List[Pod]]) -> None:
        if job.status.start_time is None:
            job.status.start_time = utcnow()
        self._count_task_statuses(job, pods_by_type)
        self.hooks.update_job_status(job, pods_by_type)
        self._write_status(job)

    def _count_task_statuses(self, job: TPUJob, pods_by_type: Dict[TaskType, List[Pod]]) -> None:
        """Reference updateJobTaskStatuses (pod.go:690-703). Failed pods that
        qualify for failover are *restarting*, not failed — they were already
        deleted/restarted by reconcile_one_pod this pass, so counting them as
        failed would flap the job into Failed (the reference distinguishes the
        same way in updateGeneralJobStatus, train/job.go:100-207)."""
        for task_type, task in job.spec.tasks.items():
            policy = task.restart_policy or RestartPolicy.NEVER
            rs = ReplicaStatus()
            for pod in pods_by_type.get(task_type, []):
                if pod.status.phase in (PodPhase.PENDING, PodPhase.RUNNING):
                    rs.active += 1
                    if pod.status.is_ready():
                        rs.ready += 1
                elif pod.status.phase == PodPhase.SUCCEEDED:
                    rs.succeeded += 1
                elif pod.status.phase == PodPhase.FAILED:
                    if pod.status.reason == "Evicted":
                        rs.evicted += 1
                    if not failover.should_pod_failover(pod, policy):
                        rs.failed += 1
            job.status.task_statuses[task_type] = rs

    def _write_status(self, job: TPUJob) -> None:
        desired = serde.deep_copy(job.status)
        desired_dict = serde.to_dict(desired, drop_none=False)

        def mutate(j: TPUJob) -> None:
            j.status = desired

        try:
            current = self.cluster.get(TPUJob, job.metadata.namespace, job.metadata.name)
            # No-op writes must be suppressed: every MODIFIED event re-enqueues
            # the job, so unconditional writes livelock the reconcile loop.
            if serde.to_dict(current.status, drop_none=False) == desired_dict:
                return
            self.cluster.update_with_retry(
                TPUJob, job.metadata.namespace, job.metadata.name, mutate,
                subresource="status")
        except NotFoundError:
            pass

    def _meter_launch_delays(self, job: TPUJob, pods: List[Pod]) -> None:
        """Launch-delay histograms (reference job.go:311-328)."""
        created = job.metadata.creation_timestamp
        if created is None or not pods:
            return
        with self._lock:
            meter = self._launch_meters.setdefault(self.job_key(job), _LaunchMeter())
        ready = [p for p in pods if p.status.is_ready() and p.status.start_time]
        if ready and not meter.first_observed:
            first = min(p.status.start_time for p in ready)
            self.metrics.first_pod_launch_delay(max(0.0, (first - created).total_seconds()))
            meter.first_observed = True
        total = sum(t.num_tasks for t in job.spec.tasks.values())
        if len(ready) >= total and total > 0 and not meter.all_observed:
            last = max(p.status.start_time for p in ready)
            self.metrics.all_pods_launch_delay(max(0.0, (last - created).total_seconds()))
            meter.all_observed = True

    # -------------------------------------------------------------- termination
    def _past_active_deadline(self, job: TPUJob) -> bool:
        deadline = job.spec.run_policy.active_deadline_seconds
        if deadline is None or job.status.start_time is None:
            return False
        return (utcnow() - job.status.start_time).total_seconds() > deadline

    def _fail_job(self, job: TPUJob, pods: List[Pod], services: List[Service],
                  reason: str, message: str) -> Result:
        conditions.update_job_conditions(job.status, JobConditionType.FAILED, reason, message)
        job.status.completion_time = job.status.completion_time or utcnow()
        self.metrics.failure()
        self.cluster.record_event(job, "Warning", reason, message)
        self._write_status(job)
        return self._finish_cleanup(job, pods, services)

    def _finish_cleanup(self, job: TPUJob, pods: List[Pod], services: List[Service]) -> Result:
        """Reference job.go:433-539: delete pods/services per clean-pod policy,
        drop podgroups, emit ModelVersion on success, handle TTL."""
        policy = job.spec.run_policy.clean_pod_policy
        for pod in pods:
            if policy == CleanPodPolicy.NONE:
                break
            if policy == CleanPodPolicy.RUNNING and pod.status.phase not in (
                PodPhase.PENDING, PodPhase.RUNNING
            ):
                continue
            try:
                self.cluster.patch_meta(
                    Pod, pod.metadata.namespace, pod.metadata.name,
                    remove_finalizers=[constants.FINALIZER_PREEMPT_PROTECTOR])
                self.cluster.delete(Pod, pod.metadata.namespace, pod.metadata.name)
            except NotFoundError:
                pass
        if policy != CleanPodPolicy.NONE:
            for svc in services:
                try:
                    self.cluster.delete(Service, svc.metadata.namespace, svc.metadata.name)
                except NotFoundError:
                    pass
        if self.gang is not None:
            self.gang.delete_podgroups(job)

        if conditions.is_succeeded(job.status):
            self._ensure_model_version(job, pods)

        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is not None:
            finished_at = job.status.completion_time or utcnow()
            age = (utcnow() - finished_at).total_seconds()
            if age >= ttl:
                # The DELETED watch event increments the deleted metric; doing
                # it here too would double-count TTL-reaped jobs.
                self.cluster.delete(TPUJob, job.metadata.namespace, job.metadata.name)
                return Result()
            return Result(requeue_after=ttl - age)
        return Result()

    # ------------------------------------------------------------ model version
    def _inject_model_path(self, job: TPUJob) -> None:
        """Inject the model output volume + env into every task container before
        pods exist (reference addModelPathEnv, job.go:557-581). Mutates only the
        in-memory job copy used for pod creation this reconcile."""
        mv = job.spec.model_version
        if mv is None:
            return
        from tpu_on_k8s.storage import volume_for_storage  # local import: L4 → storage

        volume = volume_for_storage(mv.storage)
        for task in job.spec.tasks.values():
            spec = task.template.spec
            if volume is not None and not any(v.name == volume.name for v in spec.volumes):
                spec.volumes.append(volume)
            for c in spec.containers:
                if constants.ENV_MODEL_PATH not in c.env_map():
                    c.set_env(constants.ENV_MODEL_PATH, constants.DEFAULT_MODEL_PATH)
                if volume is not None and not any(
                    m.name == volume.name for m in c.volume_mounts
                ):
                    c.volume_mounts.append(
                        VolumeMount(name=volume.name, mount_path=constants.DEFAULT_MODEL_PATH))

    def _ensure_model_version(self, job: TPUJob, pods: List[Pod]) -> None:
        """Emit a ModelVersion on success (reference creteModelVersion,
        job.go:465-508): name ``mv-{job}-{uid5}``, local storage pinned to
        master-0's node."""
        mv_spec = job.spec.model_version
        if mv_spec is None:
            return
        name = f"mv-{job.metadata.name}-{job.metadata.uid[:5]}"
        if job.status.model_version_name == name:
            if self.cluster.try_get(ModelVersion, job.metadata.namespace, name) is not None:
                return
        spec = serde.deep_copy(mv_spec)
        spec.created_by = job.metadata.name
        if spec.storage.local_storage is not None and not spec.storage.local_storage.node_name:
            spec.storage.local_storage.node_name = self._master_node(job, pods)
        mv = ModelVersion(
            metadata=ObjectMeta(
                name=name,
                namespace=job.metadata.namespace,
                labels={constants.LABEL_MODEL_NAME: spec.model_name},
                owner_references=[self.owner_ref(job)],
            ),
            spec=spec,
        )
        try:
            self.cluster.create(mv)
        except AlreadyExistsError:
            pass
        job.status.model_version_name = name
        self._write_status(job)

    @staticmethod
    def _master_node(job: TPUJob, pods: List[Pod]) -> str:
        """Node of master-0 (reference GetNodeForModelOutput,
        torchjob_controller.go:230-244)."""
        master_name = conditions.gen_general_name(job.metadata.name, TaskType.MASTER, 0)
        for pod in pods:
            if pod.metadata.name == master_name:
                return pod.spec.node_name
        return pods[0].spec.node_name if pods else ""

    # ------------------------------------------------------------- expectations
    def _expectations_satisfied(self, job: TPUJob) -> bool:
        """Gate the whole reconcile on drained expectations
        (torchjob_controller.go:190-197)."""
        key = self.job_key(job)
        for task_type in job.spec.tasks:
            for resource in ("pods", "services"):
                if not self.expectations.satisfied(
                    expectation_key(key, task_type.value, resource)
                ):
                    return False
        return True

    def release_preempt_finalizers(self, job: TPUJob) -> None:
        """Public for the DELETED event path: when the job object is already
        gone, cascade GC stamps owned pods but cannot drain the
        preempt-protector finalizer — this does."""
        self._cleanup_preempt_finalizers(job)

    def _cleanup_preempt_finalizers(self, job: TPUJob) -> None:
        for pod in self.cluster.list(Pod, job.metadata.namespace, self.job_selector(job)):
            if constants.FINALIZER_PREEMPT_PROTECTOR in pod.metadata.finalizers:
                try:
                    self.cluster.patch_meta(
                        Pod, pod.metadata.namespace, pod.metadata.name,
                        remove_finalizers=[constants.FINALIZER_PREEMPT_PROTECTOR])
                except NotFoundError:
                    pass

    # --------------------------------------------------------------- watch glue
    def observe_event(self, controller_enqueue: Callable[[str, str], None], event) -> None:
        """Pod/Service watch handler: maintain expectations and requeue the
        owning job (reference OnPodCreateFunc/OnPodUpdateFunc/OnPodDeleteFunc,
        pod.go:229-358)."""
        obj = event.obj
        ref = obj.metadata.controller_ref()
        if ref is not None and ref.kind != constants.KIND_TPUJOB:
            return
        owner_name = ref.name if ref is not None else obj.metadata.labels.get(
            constants.LABEL_JOB_NAME, "")
        if not owner_name:
            return  # orphan with no job label: not ours (pod.go:248-252)
        raw_type = obj.metadata.labels.get(constants.LABEL_TASK_TYPE, "")
        try:
            task_type = TaskType.normalize(raw_type).value
        except ValueError:
            task_type = raw_type
        resource = "pods" if obj.kind == "Pod" else "services"
        key = expectation_key(f"{obj.metadata.namespace}/{owner_name}", task_type, resource)
        if event.type == "ADDED":
            self.expectations.creation_observed(key)
        elif event.type == "DELETED":
            self.expectations.deletion_observed(key)
            if obj.kind == "Pod":
                # Release only when no live pod holds the name: under an async
                # (REST) watch, a failover recreate can land before the old
                # pod's DELETED event arrives, and the replacement inherits
                # the allocation (allocate() is idempotent per key) — freeing
                # it here would hand its port to a neighbor.
                pod_key = f"{obj.metadata.namespace}/{obj.metadata.name}"
                if self.cluster.try_get(Pod, obj.metadata.namespace,
                                        obj.metadata.name) is None:
                    self.port_allocator.release(pod_key)
        controller_enqueue(obj.metadata.namespace, owner_name)
