"""Controller expectations: in-flight create/delete accounting.

Analog of k8s ControllerExpectations as used by the reference
(/root/reference/controllers/common/expectations.go:29-66, keys built at
controllers/common/utils.go:29-36). A reconcile that creates N pods records
"expect N creations"; watch events decrement; until the count drains (or a TTL
expires) further reconciles are skipped — preventing double-creates when the
cache lags the API server.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict


def expectation_key(job_key: str, task_type: str, resource: str) -> str:
    """``{ns}/{job}/{taskType}/{pods|services}`` (reference utils.go:29-36)."""
    return f"{job_key}/{task_type.lower()}/{resource}"


@dataclass
class _Entry:
    adds: int = 0
    deletes: int = 0
    timestamp: float = 0.0


class Expectations:
    def __init__(self, ttl_seconds: float = 300.0,
                 clock=time.monotonic) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self.ttl = ttl_seconds
        # TTL expiry reads an injectable clock so expectation timeouts are
        # steerable under the simulator's virtual time (ROADMAP item 5)
        self._clock = clock

    def expect_creations(self, key: str, count: int) -> None:
        with self._lock:
            self._entries[key] = _Entry(adds=count,
                                        timestamp=self._clock())

    def expect_deletions(self, key: str, count: int) -> None:
        with self._lock:
            self._entries[key] = _Entry(deletes=count,
                                        timestamp=self._clock())

    def creation_observed(self, key: str) -> None:
        self._observe(key, d_adds=-1)

    def deletion_observed(self, key: str) -> None:
        self._observe(key, d_deletes=-1)

    def _observe(self, key: str, d_adds: int = 0, d_deletes: int = 0) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return
            e.adds = max(0, e.adds + d_adds)
            e.deletes = max(0, e.deletes + d_deletes)

    def satisfied(self, key: str) -> bool:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return True
            if e.adds <= 0 and e.deletes <= 0:
                return True
            if self._clock() - e.timestamp > self.ttl:
                # Expired expectations are treated as satisfied so a lost watch
                # event cannot wedge the job forever.
                return True
            return False

    def delete_expectations(self, key_prefix: str) -> None:
        """Drop all expectations for a job (reference expectations.go:52-66)."""
        with self._lock:
            for k in [k for k in self._entries if k.startswith(key_prefix)]:
                del self._entries[k]
