"""Native elastic autoscaler: throughput-driven, slice-legal replica scaling.

Analog of /root/reference/controllers/train/torchelastic/ (SURVEY §2.5) — the
second control loop over the same job CRD that *decides* replica counts from
observed training throughput, while the main reconciler + ElasticController
execute the resulting spec changes:

* per registered job, every ``period`` (reference: 30s,
  elastictorchjob_controller.go:60): read training metrics from worker-0's log
  stream (pods/log subresource — observation.go:40-106), parse
  ``key=value`` lines into ``MetricObservation``;
* after ``metric_count`` (5) observations at the current replica count,
  decide via the latency-per-replica test ``IsSatisfyElasticContinue``
  (job.go:94-100): if throughput still scales, grow; else revert to the last
  count and freeze (ReachMaxMetric);
* TPU twist (SURVEY §7): growth steps to the **next legal slice host count**
  (``topology.next_legal_host_count``), not the reference's free-form
  ``replicas *= 2`` (job.go:102-104) — on v5e those coincide (1,2,4,8,…), on
  3D-torus accelerators they do not;
* pending pods at a grown size revert to the last-known-good count
  (elastic_scale.go:107-122) — capacity isn't there;
* the unfinished ``GetPodsForJob -> panic("Implement me")`` seam of the
  reference (torchelastic/pod.go:25-27) simply doesn't exist here: scaling
  goes through the job spec and the engine owns pods.

Observation line format (what ``tpu_on_k8s.train`` emits):
``[elastic-metrics] epoch=3 batch=120 latency=0.245 accuracy=0.81``.
"""
from __future__ import annotations

import math
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import Pod, PodPhase
from tpu_on_k8s.api.types import ElasticStatus, TaskType, TPUJob
from tpu_on_k8s.autoscale.policy import (
    ACTION_DOWN,
    ACTION_HOLD,
    ACTION_UP,
    Decision,
)
from tpu_on_k8s.autoscale.signals import KV_RE, METRICS_TAG
from tpu_on_k8s.client.cluster import InMemoryCluster, NotFoundError
from tpu_on_k8s.controller.config import JobControllerConfig
from tpu_on_k8s.controller.elastic import ElasticController, apply_host_count
from tpu_on_k8s.controller.loopkernel import (
    LoopKernel,
    OpenHorizon,
    format_commit_failure_line,
    format_decision_line,
)
from tpu_on_k8s.gang import topology
from tpu_on_k8s.metrics.metrics import JobMetrics
from tpu_on_k8s.obs.ledger import (
    COMMIT_LANDED,
    COMMIT_NONE,
    HORIZON_REPLICAS_READY,
)
from tpu_on_k8s.utils import conditions
from tpu_on_k8s.utils.logging import get_logger

_log = get_logger("autoscaler")

# The observation-line vocabulary lives in `autoscale/signals.py` (one
# home, stdlib-only). Values are captured loosely (any non-space run)
# and validated by float() below: the old numeric-class pattern silently
# extracted digit fragments out of malformed values ("latency=x1.5"
# parsed as 1.5) instead of rejecting the line.
_KV_RE = KV_RE


@dataclass
class MetricObservation:
    """One parsed training-metrics line (reference MetricObservation,
    elastictorchjob_controller.go:99-105)."""

    epoch: int = 0
    batch: int = 0
    latency: float = 0.0
    accuracy: float = 0.0


def parse_observation(line: str) -> Optional[MetricObservation]:
    """Parse a ``[elastic-metrics] key=value ...`` line; None if not one.

    Rejected outright (None, never a zeroed observation): a missing or
    malformed ``latency``, a negative latency, and the non-finite
    ``nan``/``inf`` sentinels — ``latency=nan`` is how an emitter with
    no samples yet says "no data" (`serve/fleet.observation_line`), and
    folding it in as a number would read as infinitely fast and scale
    the consumer straight to min. Duplicate keys keep the LAST value
    (the rightmost write wins, like repeated flag parsing)."""
    if METRICS_TAG not in line:
        return None
    fields = {k: v for k, v in _KV_RE.findall(line)}
    if "latency" not in fields:
        return None
    try:
        latency = float(fields["latency"])
        obs = MetricObservation(
            epoch=int(float(fields.get("epoch", 0))),
            batch=int(float(fields.get("batch", 0))),
            latency=latency,
            accuracy=float(fields.get("accuracy", 0.0)),
        )
    except (ValueError, OverflowError):
        # OverflowError: int(float("9e999")) — an absurd epoch/batch is
        # as malformed as a non-numeric one
        return None
    if not math.isfinite(latency) or latency < 0.0:
        return None
    return obs


def is_satisfy_elastic_continue(last_replicas: int, last_latency: float,
                                cur_replicas: int, cur_latency: float) -> bool:
    """The throughput test (reference torchelastic job.go:94-100): keep
    growing while latency-per-replica improves. Both denominators are
    guarded: a zero-replica current world has no throughput to compare
    (the reference would divide by zero here) — never "keep growing"."""
    if last_replicas <= 0:
        return True
    if cur_replicas <= 0:
        return False
    return last_latency / last_replicas > cur_latency / cur_replicas


@dataclass(frozen=True)
class _ElasticPack:
    """One elastic tick's evidence, frozen at observe time (decide
    mutates the job's ElasticStatus, so the ledger's signal snapshot
    must be captured before it does)."""

    job: TPUJob
    status: ElasticStatus
    cur: int
    last_replicas: int
    last_latency: float
    #: pending-pods revert: the grown size is not materializing and the
    #: grace ran out — revert to this count (None = normal metric tick)
    revert_to: Optional[int] = None
    #: mean latency of the decision window (None on a revert tick)
    cur_latency: Optional[float] = None


class _JobState(LoopKernel):
    """One elastic job's decision loop on the shared observe→decide→
    commit kernel (`controller/loopkernel.py`): observe tails worker-0's
    log into watermarked per-replica buckets, decide runs the
    latency-per-replica continue test, commit executes the rescale (or
    freeze) through the cluster client — and every decision lands one
    ledger record, uniformly with the serving loops."""

    #: the owning controller, TYPED (set before run_tick) — the
    #: concurrency analyzer's call graph follows hook→controller edges
    #: through this attribute (see _AutoscaleLoop.owner)
    owner: Optional["ElasticAutoscaler"] = None

    def bind_owner(self, owner: "ElasticAutoscaler") -> None:
        self.owner = owner

    def __init__(self, observations: Optional[Dict[int, List[
            MetricObservation]]] = None, frozen: bool = False,
            watermark: Optional[tuple] = None,
            pending_ticks: int = 0) -> None:
        super().__init__()
        self.observations: Dict[int, List[MetricObservation]] = (
            observations if observations is not None else {})
        #: ReachMaxMetric / ReachMaxReplicas: stop deciding
        self.frozen = frozen
        # Only metric lines strictly newer than this (epoch, batch)
        # watermark count toward the current replica bucket — worker-0's
        # log tail still holds pre-scale lines right after a rescale,
        # and deciding on those would race the scaler to max_replicas on
        # zero post-scale evidence.
        self.watermark = watermark
        #: consecutive ticks with Pending workers at grown size
        self.pending_ticks = pending_ticks

    # ------------------------------------------------------------ kernel hooks
    def observe(self, ctx) -> Optional[_ElasticPack]:
        """Everything short of a decision: hold while a scale transaction
        is in flight, while stale-generation pods linger, while the
        world assembles, while frozen, and until the decision window is
        full. None = no decision exists this tick."""
        a = self.owner
        job = ctx["job"]
        worker = job.spec.tasks.get(TaskType.WORKER)
        ep = job.spec.elastic_policy
        if worker is None or ep is None:
            return None
        status = a._elastic_status(job)
        cur = worker.num_tasks

        # Hold while a scale transaction is executing (stale pods / inflight).
        if job.metadata.annotations.get(
                constants.ANNOTATION_SCALE_STATE) == \
                constants.SCALE_STATE_INFLIGHT:
            return None
        pods = a.cluster.list(Pod, job.metadata.namespace,
                              {constants.LABEL_JOB_NAME: job.metadata.name})
        workers = [p for p in pods if p.metadata.labels.get(
            constants.LABEL_TASK_TYPE) == TaskType.WORKER.value.lower()]
        if any(int(p.metadata.labels.get(constants.LABEL_JOB_GENERATION,
                                         "0") or 0)
               < job.metadata.generation for p in pods):
            return None

        pending = [p for p in workers if p.status.phase == PodPhase.PENDING]
        if pending and cur > ep.min_replicas and status.last_replicas > 0:
            # Grown size not materializing. Grace-period the revert
            # (reference polls up to 1min, elastic_scale.go:440-474): a
            # tick landing in a normal seconds-long scheduling window
            # must not kill autoscaling.
            self.pending_ticks += 1
            if self.pending_ticks >= a.config.elastic_pending_grace_ticks:
                self.seq += 1
                return _ElasticPack(job, status, cur,
                                    status.last_replicas,
                                    status.last_latency,
                                    revert_to=status.last_replicas)
            return None
        self.pending_ticks = 0
        if len(workers) < cur or pending:
            return None  # world still assembling
        if self.frozen:
            return None  # no decisions → no log tailing either

        obs = a._collect_observations(job, self, cur)
        if len(obs) < a.config.elastic_metric_count:
            return None
        window = obs[-a.config.elastic_metric_count:]
        cur_latency = sum(o.latency for o in window) / len(window)
        status.current_latency = cur_latency
        self.seq += 1
        return _ElasticPack(job, status, cur, status.last_replicas,
                            status.last_latency, cur_latency=cur_latency)

    def decide(self, pack: _ElasticPack, ctx) -> Decision:
        """The throughput continue-test (reference order,
        elastic_scale.go:186-233: continue-test FIRST — a regression at
        max replicas must still revert to the last-good size). The
        decision KIND rides ``ctx`` to commit; the Decision itself is
        the shared loop vocabulary the log and ledger serialize."""
        a = self.owner
        job = ctx["job"]
        status, cur = pack.status, pack.cur
        ep = job.spec.elastic_policy
        if pack.revert_to is not None:
            ctx["elastic_kind"] = "revert"
            return Decision(self.seq,
                            ACTION_DOWN if pack.revert_to < cur
                            else ACTION_HOLD, cur, pack.revert_to,
                            "pending pods at grown size; reverting")
        if is_satisfy_elastic_continue(status.last_replicas,
                                       status.last_latency,
                                       cur, pack.cur_latency):
            nxt = None if cur >= ep.max_replicas else \
                a._next_host_count(job, cur, ep.max_replicas)
            if nxt is None:
                ctx["elastic_kind"] = "freeze_max_replicas"
                return Decision(self.seq, ACTION_HOLD, cur, cur,
                                "ReachMaxReplicas")
            status.last_replicas = cur
            status.last_latency = pack.cur_latency
            status.continue_scaling = True
            status.message = f"scaling {cur} -> {nxt} hosts"
            ctx["elastic_kind"] = "grow"
            return Decision(self.seq, ACTION_UP, cur, nxt,
                            f"scaling {cur} -> {nxt} hosts")
        # Throughput stopped scaling: best config is the previous one.
        ctx["elastic_kind"] = "freeze_max_metric"
        target = status.last_replicas or cur
        return Decision(self.seq,
                        ACTION_DOWN if target < cur else ACTION_HOLD,
                        cur, target, "ReachMaxMetric")

    def record(self, pack: _ElasticPack, decision, ctx) -> None:
        self.owner.decision_log.append(format_decision_line(
            decision.seq, decision.action, decision.current,
            decision.target, decision.reason,
            scope=(("job", ctx["key"]),)))

    def actionable(self, decision, ctx) -> bool:
        # every elastic decision executes SOMETHING (a rescale, a
        # freeze-with-status-write) — the kind dispatch lives in commit
        return True

    def commit(self, pack: _ElasticPack, decision, ctx) -> str:
        a = self.owner
        job = ctx["job"]
        status = pack.status
        kind = ctx["elastic_kind"]
        if kind == "freeze_max_replicas":
            self.frozen = True
            status.continue_scaling = False
            status.message = "ReachMaxReplicas"
            a._write_status(job)
            return COMMIT_NONE       # nothing scaled: a frozen hold
        if kind == "revert":
            a._rescale(job, status, self, decision.target,
                       message="pending pods at grown size; reverting",
                       freeze=True)
            return COMMIT_LANDED
        if kind == "freeze_max_metric":
            status.message = "ReachMaxMetric"
            a._rescale(job, status, self, decision.target, freeze=True)
            return COMMIT_LANDED
        if a.broker is not None and not a.broker.request_capacity(
                f"train/{ctx['key']}", decision.current, decision.target):
            # the capacity-market gate, pre-rescale: a refusal means the
            # grow never happened — no watermark reset, no status write,
            # no freeze — and the loop re-decides at full speed next
            # tick while the broker's ladder works the shortfall; the
            # grant lands whenever pressure clears
            a.decision_log.append(format_commit_failure_line(
                decision.seq, "BrokerRefused",
                scope=(("job", ctx["key"]),)))
            return "conflict:BrokerRefused"
        a._rescale(job, status, self, decision.target)
        return COMMIT_LANDED

    # -------------------------------------------------------- provenance hooks
    def opens_horizon(self, decision, outcome: str, ctx) -> bool:
        """A rescale that also FREEZES the loop (pending-revert,
        ReachMaxMetric) leaves no future tick to observe its effect —
        opening a horizon there would pin the open_effect_horizons
        gauge forever and read as a standing 'effects never land'
        alert on every normally-converged job."""
        return ctx.get("elastic_kind") == "grow"

    def signals_of(self, pack: _ElasticPack):
        fmt = (lambda v: "none" if v is None else f"{v:.6f}")
        return (("latency", fmt(pack.cur_latency)),
                ("last_latency", fmt(pack.last_latency)),
                ("last_replicas", str(pack.last_replicas)))

    def horizon_events(self, h: OpenHorizon, pack: _ElasticPack, ctx):
        # a metric tick only exists once the world assembled at the new
        # size AND post-scale evidence filled the window — exactly the
        # "replicas went ready" observation (a revert tick proves the
        # opposite and must not close anything)
        if pack.revert_to is None and pack.cur == h.target:
            return ((HORIZON_REPLICAS_READY, True),)
        return ()


class ElasticAutoscaler:
    """The decision loop. ``run_once()`` is the deterministic unit tests and
    the local driver call; ``run()`` wraps it in a background thread at the
    reference's 30s cadence."""

    def __init__(self, cluster: InMemoryCluster,
                 config: Optional[JobControllerConfig] = None,
                 metrics: Optional[JobMetrics] = None,
                 ledger=None, broker=None) -> None:
        self.cluster = cluster
        self.config = config or JobControllerConfig()
        self.metrics = metrics
        # the capacity broker (`coordinator/broker.CapacityBroker`):
        # set, every grow asks for chips before the rescale (a refusal
        # is ``conflict:BrokerRefused`` — the loop retries next tick)
        # and the job becomes a bidder (``train/<key>``) the broker's
        # rung-3 preemption can shrink through ``shrink_to`` — the
        # live-reshard path with its cold-restart fallback, never a
        # kill. None → market-free operation, byte-identical.
        self.broker = broker
        # the decision ledger (`obs/ledger.DecisionLedger`): every
        # elastic decision lands one provenance record through the loop
        # kernel, uniformly with the serving loops. None → NOOP.
        self.ledger = ledger
        #: stable one-line-per-decision record in the shared serializer
        #: format (``job=<ns/name> seq=N action=... replicas=c->t
        #: reason=...``) — the elastic twin of the FleetAutoscaler's
        #: byte-comparable log. Bounded like its sibling.
        self.decision_log: Deque[str] = deque(maxlen=10_000)
        self._lock = threading.Lock()
        self._jobs: Dict[str, _JobState] = {}  # "ns/name" → state
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ registration
    def register(self, job: TPUJob) -> None:
        """Jobs enter via the create-watch (reference eventhandler.go:25-66);
        only native-elastic jobs (elastic_policy set) qualify."""
        if job.spec.elastic_policy is None:
            return
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        with self._lock:
            self._jobs.setdefault(key, _JobState())
        self._broker_register(key)

    def deregister(self, job: TPUJob) -> None:
        key = f"{job.metadata.namespace}/{job.metadata.name}"
        with self._lock:
            state = self._jobs.pop(key, None)
        if state is not None:
            # a deleted-mid-scale job must not leave an unclosable
            # horizon pinning the shared ledger's gauge
            state.abandon()
            self._broker_deregister(key)

    def observe_event(self, event) -> None:
        """Watch glue: register on ADDED, deregister on DELETED."""
        if event.kind != constants.KIND_TPUJOB:
            return
        if event.type == "ADDED":
            self.register(event.obj)
        elif event.type == "DELETED":
            self.deregister(event.obj)

    def registered(self) -> List[str]:
        with self._lock:
            return sorted(self._jobs)

    # ------------------------------------------------------------ decision loop
    def run_once(self) -> None:
        with self._lock:
            keys = list(self._jobs.items())
        for key, state in keys:
            ns, name = key.split("/", 1)
            job = self.cluster.try_get(TPUJob, ns, name)
            if job is None or conditions.is_finished(job.status):
                with self._lock:
                    self._jobs.pop(key, None)
                state.abandon()
                self._broker_deregister(key)
                continue
            # the kernel template drives observe→decide→commit and
            # lands one ledger record per decision (hooks on _JobState
            # above). NB `state` stays deliberately untyped here: the
            # concurrency analyzer's virtual-dispatch closure merges the
            # type worlds of every kernel subclass reachable from a
            # root, and typing this call would fuse the elastic and
            # fleet tick drivers into one multi-root blur (the hooks
            # reach the controller through the TYPED `owner` attribute,
            # so the cluster-mutation paths stay in the analyzed graph)
            state.bind(f"elasticautoscaler/{key}", self.ledger)
            state.bind_owner(self)
            try:
                state.run_tick({"job": job, "key": key})
            except NotFoundError:
                continue

    # --------------------------------------------------------- capacity market
    def _broker_register(self, key: str) -> None:
        """Make the job a bidder on the capacity market (idempotent —
        re-registering would reset the lane's ledger loop). The bid and
        shrink closures run on the BROKER's tick thread and touch only
        the cluster client and ``shrink_to`` — which takes this
        autoscaler's lock briefly for the state lookup, never while the
        broker holds its own, so no lock-order cycle exists."""
        broker = self.broker
        if broker is None:
            return
        name = f"train/{key}"
        if name in broker.consumers():
            return
        broker.register(
            name,
            lambda: self._training_bid(key),
            apply_fn=lambda target, reason: self.shrink_to(
                key, target, reason=reason))

    def _broker_deregister(self, key: str) -> None:
        if self.broker is not None:
            self.broker.deregister(f"train/{key}")

    def _training_bid(self, key: str):
        """The job's standing bid: hold its current worker gang (growth
        arrives through the ``request_capacity`` gate in commit),
        floored at ``elastic_policy.min_replicas`` — the broker's
        rung-3 preemption can shrink the gang down to the floor but
        never below, and never touches a non-elastic job at all."""
        from tpu_on_k8s.coordinator.broker import (
            KIND_TRAINING, PRIORITY_TRAINING, Bid)
        ns, name = key.split("/", 1)
        job = self.cluster.try_get(TPUJob, ns, name)
        if job is None or conditions.is_finished(job.status):
            return None
        ep = job.spec.elastic_policy
        worker = job.spec.tasks.get(TaskType.WORKER)
        if ep is None or worker is None:
            return None
        cur = max(int(worker.num_tasks), 0)
        return Bid(name=f"train/{key}", kind=KIND_TRAINING,
                   priority=PRIORITY_TRAINING, current=cur, desired=cur,
                   floor=max(int(ep.min_replicas), 0), unit=1,
                   preemption_cost=float(cur))

    def shrink_to(self, key: str, hosts: int, *, reason: str = "") -> bool:
        """Broker-pushed preemption (ladder rung 3): shrink the job's
        worker gang to ``hosts`` through the SAME path an elastic
        decision takes — ``apply_host_count`` slice legality, a
        live-reshard request when the policy allows one, the
        checkpoint-restart fallback otherwise — WITHOUT freezing the
        continue-test: when pressure clears, the loop's next grow asks
        the broker again and wins its chips back. Clamped to
        ``min_replicas``; already at/below target is a success."""
        ns, name = key.split("/", 1)
        with self._lock:
            state = self._jobs.get(key)
        job = self.cluster.try_get(TPUJob, ns, name)
        if state is None or job is None \
                or conditions.is_finished(job.status):
            return False
        ep = job.spec.elastic_policy
        worker = job.spec.tasks.get(TaskType.WORKER)
        if ep is None or worker is None:
            return False
        target = max(int(hosts), int(ep.min_replicas))
        if target >= worker.num_tasks:
            return True
        status = self._elastic_status(job)
        try:
            self._rescale(job, status, state, target,
                          message=reason
                          or f"broker preempt to {target} hosts")
        except NotFoundError:
            return False
        return True

    def _next_host_count(self, job: TPUJob, cur: int, cap: int) -> Optional[int]:
        """One growth step: multi-slice jobs add a slice (DCN); single-slice
        jobs step to the next legal topology host count (ICI-preferred),
        falling over to a second slice only once the topology maxes out."""
        tpu = job.spec.tpu_policy
        per_slice = topology.hosts_per_slice(tpu.accelerator, tpu.topology)
        if tpu.num_slices > 1:
            nxt = cur + per_slice
        else:
            nxt = topology.next_legal_host_count(tpu.accelerator, cur)
            if nxt is None:
                nxt = cur + per_slice
        return None if nxt > cap else nxt

    # --------------------------------------------------------------- mechanics
    def _collect_observations(self, job: TPUJob, state: _JobState,
                              replicas: int) -> List[MetricObservation]:
        """getMetricsObservation (observation.go:40-106): tail worker-0's log.
        Lines at/below the rescale watermark belong to the previous world size
        and are excluded; buckets are bounded."""
        worker0 = conditions.gen_general_name(job.metadata.name, TaskType.WORKER, 0)
        lines = self.cluster.read_pod_log(
            job.metadata.namespace, worker0,
            tail=self.config.elastic_metric_count * 4)
        parsed = [o for o in (parse_observation(l) for l in lines) if o is not None]
        bucket = state.observations.setdefault(replicas, [])
        seen = {(o.epoch, o.batch) for o in bucket}
        cap = self.config.elastic_metric_count * 4
        for o in parsed:
            key = (o.epoch, o.batch)
            if state.watermark is not None and key <= state.watermark:
                continue
            if key not in seen:
                bucket.append(o)
                seen.add(key)
        del bucket[:-cap]
        return bucket

    def _rescale(self, job: TPUJob, status: ElasticStatus, state: _JobState,
                 hosts: int, *, message: str = "", freeze: bool = False) -> None:
        if message:
            status.message = message
        if freeze:
            state.frozen = True
            status.continue_scaling = False
        # Advance the watermark past everything seen so far: post-scale
        # decisions must rest on post-scale evidence only.
        keys = [(o.epoch, o.batch)
                for bucket in state.observations.values() for o in bucket]
        if keys:
            state.watermark = max(keys)
        state.observations.clear()

        worker = job.spec.tasks.get(TaskType.WORKER)
        prev_hosts = worker.num_tasks if worker is not None else 0
        applied = [0]

        def mutate(j: TPUJob) -> None:
            applied[0] = apply_host_count(j, hosts)

        updated = self.cluster.update_with_retry(
            TPUJob, job.metadata.namespace, job.metadata.name, mutate)
        status.replicas = applied[0]
        ep = job.spec.elastic_policy
        if (ep is not None and ep.live_reshard and applied[0] > 0
                and applied[0] != prev_hosts):
            # the decision is a (hosts, mesh shape) PAIR: deliver it to
            # the pods as a live-reshard request (`parallel/reshard.py`)
            # instead of leaving the cold restart as the only executor
            self._request_live_reshard(updated, applied[0])
        self._write_status(job)
        self.cluster.record_event(
            job, "Normal", "ElasticRescale",
            f"autoscaler: {status.message or f'scale to {applied[0]} hosts'}")

    def _request_live_reshard(self, job: TPUJob, hosts: int) -> None:
        """Stamp the post-respec job with the (hosts, mesh shape) reshard
        request. The mesh shape is derived from the new slice
        configuration under `gang/topology` legality (axis product ==
        chip count); a configuration with no legal default shape leaves
        the cold checkpoint-restart path in charge, with the reason on
        the event stream."""
        tpu = job.spec.tpu_policy
        try:
            mesh = topology.mesh_shape_for_slice(
                tpu.accelerator, tpu.topology, tpu.num_slices)
        except (KeyError, ValueError) as e:
            self.cluster.record_event(job, "Warning", "LiveReshardSkipped",
                                      f"no slice-legal mesh shape: {e}")
            return
        spec = topology.format_reshard_spec(
            job.metadata.generation, hosts, mesh)
        try:
            self.cluster.patch_meta(
                TPUJob, job.metadata.namespace, job.metadata.name,
                annotations={
                    constants.ANNOTATION_RESHARD_REQUESTED_SPEC: spec})
        except NotFoundError:
            return
        self.cluster.record_event(job, "Normal", "LiveReshardRequested",
                                  f"reshard request: {spec}")

    def _elastic_status(self, job: TPUJob) -> ElasticStatus:
        status = job.status.elastic_statuses.get(TaskType.WORKER)
        if status is None:
            status = ElasticStatus(
                replicas=job.spec.tasks[TaskType.WORKER].num_tasks)
            job.status.elastic_statuses[TaskType.WORKER] = status
        return status

    def _write_status(self, job: TPUJob) -> None:
        desired = job.status.elastic_statuses

        def mutate(j: TPUJob) -> None:
            j.status.elastic_statuses = desired

        try:
            self.cluster.update_with_retry(
                TPUJob, job.metadata.namespace, job.metadata.name, mutate,
                subresource="status")
        except NotFoundError:
            pass

    # ----------------------------------------------------------------- run loop
    def run(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.run_once()
                except Exception:
                    # a crashing decision loop must never disappear silently:
                    # surface it in the log AND the errors_total counter
                    _log.exception("elastic autoscaler tick failed")
                    if self.metrics is not None:
                        self.metrics.error()
                self._stop.wait(self.config.elastic_loop_period_seconds)

        # start before publishing: stop() must never observe (and join) a
        # created-but-unstarted thread
        t = threading.Thread(target=loop, daemon=True, name="elastic-autoscaler")
        t.start()
        self._thread = t

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)


def setup_elastic_autoscaler(cluster: InMemoryCluster,
                             config: Optional[JobControllerConfig] = None,
                             metrics: Optional[JobMetrics] = None,
                             ledger=None, broker=None) -> ElasticAutoscaler:
    """Wire the autoscaler's job registry to the cluster watch (reference
    SetupWithManager, torchelastic/elastictorchjob_controller.go:128-148)."""
    scaler = ElasticAutoscaler(cluster, config=config, metrics=metrics,
                               ledger=ledger, broker=broker)
    cluster.watch(scaler.observe_event)
    return scaler
