"""Minimal controller runtime: workqueue, rate limiting, reconcile pump.

The role controller-runtime plays for the reference (workqueue → Reconcile cycle,
SURVEY §3.2 "hot loop"). Deterministic and synchronous-first: tests and the local
driver call ``Manager.run_until_idle()``; a background-thread mode exists for a
live deployment.
"""
from __future__ import annotations

import heapq
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, NamedTuple, Optional, Set, Tuple

from tpu_on_k8s.utils.logging import get_logger

_log = get_logger("runtime")


class Request(NamedTuple):
    namespace: str
    name: str


@dataclass
class Result:
    requeue: bool = False
    requeue_after: Optional[float] = None  # seconds


class ExponentialBackoff:
    """Per-item exponential backoff (k8s DefaultItemBasedRateLimiter analog).
    Also the BackoffStatesQueue the reference uses to count job restarts
    (controllers/common/controller.go BackoffStatesQueue): ``failures`` is the
    retry count consulted by the backoff-limit termination check."""

    def __init__(self, base: float = 0.005, cap: float = 30.0) -> None:
        self.base = base
        self.cap = cap
        self._failures: Dict[Request, int] = {}
        self._lock = threading.Lock()

    def next_delay(self, item: Request) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return min(self.base * (2 ** n), self.cap)

    def failures(self, item: Request) -> int:
        with self._lock:
            return self._failures.get(item, 0)

    def forget(self, item: Request) -> None:
        with self._lock:
            self._failures.pop(item, None)


class Workqueue:
    """Deduplicating delayed workqueue with get/done semantics: an item re-added
    while processing is marked dirty and re-queued on done()."""

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._lock = threading.Condition()
        self._queue: List[Request] = []
        self._queued: Set[Request] = set()
        self._processing: Set[Request] = set()
        self._dirty: Set[Request] = set()
        self._delayed: List[Tuple[float, int, Request]] = []
        self._seq = 0

    def add(self, item: Request) -> None:
        with self._lock:
            if item in self._processing:
                self._dirty.add(item)
                return
            if item not in self._queued:
                self._queued.add(item)
                self._queue.append(item)
                self._lock.notify()

    def add_after(self, item: Request, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._lock:
            self._seq += 1
            heapq.heappush(self._delayed, (self._clock() + delay, self._seq, item))

    def _promote_due(self) -> None:
        now = self._clock()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, item = heapq.heappop(self._delayed)
            if item not in self._queued and item not in self._processing:
                self._queued.add(item)
                self._queue.append(item)
            elif item in self._processing:
                self._dirty.add(item)

    def try_get(self) -> Optional[Request]:
        with self._lock:
            self._promote_due()
            if not self._queue:
                return None
            item = self._queue.pop(0)
            self._queued.discard(item)
            self._processing.add(item)
            return item

    def done(self, item: Request) -> None:
        with self._lock:
            self._processing.discard(item)
            if item in self._dirty:
                self._dirty.discard(item)
                if item not in self._queued:
                    self._queued.add(item)
                    self._queue.append(item)

    def next_due_in(self) -> Optional[float]:
        with self._lock:
            self._promote_due()
            if self._queue:
                return 0.0
            if self._delayed:
                return max(0.0, self._delayed[0][0] - self._clock())
            return None

    def __len__(self) -> int:
        with self._lock:
            self._promote_due()
            return len(self._queue) + len(self._delayed)


@dataclass
class Controller:
    name: str
    reconcile: Callable[[Request], Result]
    queue: Workqueue = field(default_factory=Workqueue)
    rate_limiter: ExponentialBackoff = field(default_factory=ExponentialBackoff)

    def enqueue(self, namespace: str, name: str) -> None:
        self.queue.add(Request(namespace, name))

    def enqueue_after(self, namespace: str, name: str, delay: float) -> None:
        self.queue.add_after(Request(namespace, name), delay)

    def process_one(self) -> bool:
        item = self.queue.try_get()
        if item is None:
            return False
        try:
            result = self.reconcile(item)
        except Exception:
            self.queue.done(item)
            self.queue.add_after(item, self.rate_limiter.next_delay(item))
            raise
        self.queue.done(item)
        if result.requeue_after is not None:
            self.queue.add_after(item, result.requeue_after)
        elif result.requeue:
            self.queue.add_after(item, self.rate_limiter.next_delay(item))
        else:
            self.rate_limiter.forget(item)
        return True


class Manager:
    """Pumps all controllers to quiescence (tests / local driver) or runs them on
    worker threads (live mode)."""

    def __init__(self) -> None:
        self.controllers: List[Controller] = []
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    def add_controller(self, controller: Controller) -> Controller:
        self.controllers.append(controller)
        return controller

    def run_until_idle(self, *, max_iterations: int = 10_000,
                       advance: Optional[Callable[[float], None]] = None) -> int:
        """Process work until every queue is empty (including delayed items if a
        test clock `advance` is provided). Returns reconcile count. Raises if the
        iteration budget is exhausted (reconcile livelock guard)."""
        processed = 0
        for _ in range(max_iterations):
            progressed = False
            for c in self.controllers:
                while c.process_one():
                    processed += 1
                    progressed = True
            if progressed:
                continue
            if advance is not None:
                dues = [d for d in (c.queue.next_due_in() for c in self.controllers)
                        if d is not None]
                if dues:
                    advance(min(dues) + 1e-6)
                    continue
            return processed
        raise RuntimeError(f"run_until_idle: no quiescence after {max_iterations} iterations")

    def start(self, workers_per_controller: int = 1) -> None:
        self._stop.clear()
        for c in self.controllers:
            for i in range(workers_per_controller):
                t = threading.Thread(target=self._worker, args=(c,), daemon=True,
                                     name=f"{c.name}-worker-{i}")
                t.start()
                self._threads.append(t)

    def _worker(self, c: Controller) -> None:
        while not self._stop.is_set():
            try:
                if not c.process_one():
                    due = c.queue.next_due_in()
                    self._stop.wait(min(due, 0.05) if due is not None else 0.05)
            # analyze: allow[silent-loss] process_one already re-queued the item with rate-limited backoff; logged here
            except Exception:  # reconcile errors are retried via backoff
                _log.exception("reconcile failed (will retry with backoff)",
                               extra={"kv": {"controller": c.name}})

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
