"""DAG gating of task creation.

Analog of /root/reference/controllers/common/dag.go:30-116: a task type's pods are
only created once each upstream task type has all replicas at-or-past the required
phase. Default edges (AIMaster→Master→Worker) are injected by defaulting.
"""
from __future__ import annotations

from typing import Dict, List

from tpu_on_k8s.api.core import Pod, PodPhase
from tpu_on_k8s.api.types import TaskSpec, TaskType, TPUJob

# Phase ordering codes (dag.go:111-116): a pod at phase >= required satisfies the
# gate, except terminal Failed/Unknown never satisfies a Running requirement.
_PHASE_RANK = {
    PodPhase.PENDING: 0,
    PodPhase.RUNNING: 1,
    PodPhase.SUCCEEDED: 2,
    PodPhase.FAILED: -1,
    PodPhase.UNKNOWN: -2,
}


def upstream_tasks_ready(
    job: TPUJob,
    upstream: TaskType,
    required_phase: str,
    pods_by_type: Dict[TaskType, List[Pod]],
) -> bool:
    """All replicas of ``upstream`` exist and are at/past ``required_phase``
    (dag.go:83-109)."""
    spec = job.spec.tasks.get(upstream)
    if spec is None:
        return True  # edge to a task type the job doesn't declare: vacuous
    pods = pods_by_type.get(upstream, [])
    if len(pods) < spec.num_tasks:
        return False
    need = _PHASE_RANK.get(required_phase, 1)
    ok = 0
    for pod in pods:
        rank = _PHASE_RANK.get(pod.status.phase, -2)
        if rank >= need and rank >= 0:
            ok += 1
    return ok >= spec.num_tasks


def dag_conditions_ready(
    job: TPUJob,
    task_type: TaskType,
    pods_by_type: Dict[TaskType, List[Pod]],
) -> bool:
    """All DAG edges into ``task_type`` are satisfied (dag.go:30-54)."""
    spec = job.spec.tasks.get(task_type)
    if spec is None:
        return True
    for cond in spec.dag_conditions:
        if not upstream_tasks_ready(job, cond.upstream, cond.on_phase, pods_by_type):
            return False
    return True
