"""InferenceService controller: deploy the model image as a serving fleet.

The ModelVersion controller (`controller/modelversion.py`) ends at
``Model.status.latest_image`` — an OCI image nothing deploys. This
controller closes the loop: an ``InferenceService`` names a ``Model``
(or pins an image) and the reconciler converges a fleet of
**gang-scheduled replica pods** onto it:

* each replica is one TPU slice — a gang of ``hosts_per_slice`` pods
  sharing a podgroup annotation, with the GKE slice nodeSelectors and
  ``google.com/tpu`` chip requests the TPUJob reconciler uses
  (`controller/tpujob.py` set_cluster_spec);
* a new image (a fresh ModelVersion landing on the Model) triggers a
  **rolling rollout**: surge new-version replicas within
  ``rollout.max_surge``, wait for their gangs to come Ready, then
  **drain** old replicas — annotate them with a drain deadline (the
  serve plane's ``stop_accepting()``; in-flight requests finish) and
  only delete the pods once the deadline passes — never letting ready
  capacity dip below ``replicas - max_unavailable``;
* ``status.canary_weight`` is the single number the serve-plane router
  (`serve/router.py`) needs: the traffic share currently granted to
  ``target_image`` — ``rollout.canary_weight`` once the first new
  replica is ready, growing with the replaced fraction, 1.0 at
  completion. Controller rollout position and router traffic split can
  therefore never disagree.
* a ``spec.decode`` change (`DecodePolicy`: int8 serving weights,
  speculative draft) folds into the replica-group identity hash
  (``decode_variant``), so flipping int8 or the draft rides the SAME
  rollout machinery — the int8 variant is canaried under live traffic,
  never hot-swapped into running pods.
* a ``spec.sharding`` change (`ShardingPolicy`: the replica's
  ``{data, model, expert}`` mesh shape + rule preset) folds into the
  same identity hash and threads ``--mesh-*``/``--shard-rules`` args to
  the replica pods — a RESHARDING rolls the fleet exactly like a new
  image (params cannot be relaid out under a live engine's compiled
  programs), and the canary split A/Bs the new mesh under live traffic
  before the fleet commits.

The in-process twin of this state machine — same phases, same
surge/drain ordering, driven per engine step instead of per reconcile —
lives in `serve/fleet.py` and is what the zero-loss rollout test pins.
"""
from __future__ import annotations

import hashlib
import time
from typing import Callable, Dict, List, Optional, Tuple

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import (
    Container,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
    PodSpec,
)
from tpu_on_k8s.api.inference_types import (
    InferenceService,
    ModelStatus,
    RolloutPolicy,
    ServicePhase,
)
from tpu_on_k8s.obs.trace import ensure as ensure_tracer
from tpu_on_k8s.api.model_types import Model
from tpu_on_k8s.client.cluster import (
    AlreadyExistsError,
    InMemoryCluster,
    NotFoundError,
    WatchEvent,
)
from tpu_on_k8s.controller.config import JobControllerConfig
from tpu_on_k8s.controller.runtime import (
    Controller,
    Manager,
    Request,
    Result,
    Workqueue,
)
from tpu_on_k8s.gang import topology
from tpu_on_k8s.utils.logging import get_logger

_log = get_logger("inferenceservice")


def image_hash(image: str) -> str:
    """Label-safe short content hash of an image ref (image refs carry
    '/' and ':', which label values forbid)."""
    return hashlib.sha1(image.encode()).hexdigest()[:8]


def decode_variant(image: str, decode, sharding=None, *,
                   pooled: bool = False) -> str:
    """The rollout identity of (image, DecodePolicy, ShardingPolicy):
    the decode policy and the mesh shape are part of what a replica
    RUNS (int8 weights, a speculative draft, the parallelism its
    compiled programs were laid out for), so flipping either must roll
    the fleet — surge, drain, canary split — exactly like a new image,
    never mutate pods in place. Only knobs that actually change the
    replica's serve args enter the identity: ``None``, an all-defaults
    block, and a ``spec_k`` with no draft all map to the bare image ref
    — applying ``decode: {}`` or ``sharding: {}`` to a running fleet
    must not trigger a full no-op rollout."""
    tags = []
    if decode is not None:
        d = decode.normalized()
        if d.draft_model:
            tags.append(f"draft={d.draft_model},k={d.spec_k}")
        if d.int8_weights:
            tags.append("int8=1")
    if sharding is not None:
        s = sharding.normalized()
        if not s.is_trivial():
            tags.append(f"mesh=d{s.data}m{s.model}e{s.expert}"
                        f",rules={s.rules}")
    if pooled:
        # ONLY the mode bit, never the member list: pool membership
        # converges by weight hot-swap through status.models — folding
        # the refs in would roll the fleet on every membership edit,
        # defeating the hot-swap entirely
        tags.append("pool=1")
    if not tags:
        return image
    return image + "#" + ";".join(tags)


class _ReplicaGroup:
    """One replica gang's observed pods (same image hash + ordinal)."""

    def __init__(self, hash_: str, index: int, hosts: int) -> None:
        self.hash = hash_
        self.index = index
        self.hosts = hosts
        self.pods: List[Pod] = []

    @property
    def ready(self) -> bool:
        """The whole gang is Running and Ready — a partially-up slice
        cannot serve (the gang is one failure domain)."""
        return (len(self.pods) == self.hosts
                and all(p.status.phase == PodPhase.RUNNING
                        and p.status.is_ready() for p in self.pods))

    @property
    def failed(self) -> bool:
        return any(p.status.phase == PodPhase.FAILED for p in self.pods)

    @property
    def draining(self) -> bool:
        return any(constants.ANNOTATION_SERVING_DRAIN_DEADLINE
                   in p.metadata.annotations for p in self.pods)

    def drain_deadline(self) -> Optional[float]:
        vals = [float(p.metadata.annotations[
            constants.ANNOTATION_SERVING_DRAIN_DEADLINE])
            for p in self.pods
            if constants.ANNOTATION_SERVING_DRAIN_DEADLINE
            in p.metadata.annotations]
        return min(vals) if vals else None


class InferenceServiceReconciler:
    """Level-triggered: every pass re-derives the rollout position from
    the observed pods (their image-hash labels), so a controller restart
    mid-rollout resumes exactly where the fleet actually is."""

    def __init__(self, cluster: InMemoryCluster,
                 config: Optional[JobControllerConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 tracer=None) -> None:
        self.cluster = cluster
        self.config = config or JobControllerConfig()
        self.clock = clock
        # one ``reconcile.inferenceservice`` span per pass
        # (`tpu_on_k8s/obs/trace.py`) — control-plane convergence on the
        # same timeline as the serve-plane request spans
        self._tracer = ensure_tracer(tracer)

    # ------------------------------------------------------------- reconcile
    def reconcile(self, request: Request) -> Result:
        with self._tracer.span("reconcile.inferenceservice",
                               namespace=request.namespace,
                               name=request.name) as sp:
            res = self._reconcile(request, sp)
            sp.set(requeue_after=res.requeue_after)
            return res

    def _reconcile(self, request: Request, sp) -> Result:
        svc = self.cluster.try_get(InferenceService, request.namespace,
                                   request.name)
        if svc is None:
            return Result()   # owner refs garbage-collect the pods
        image = self._target_image(svc)
        if not image:
            self._set_status(svc, phase=ServicePhase.PENDING,
                             message=f"waiting for model "
                                     f"{svc.spec.model_name!r} to publish "
                                     f"an image")
            return Result(requeue_after=self.config.sync_period_seconds)

        models = svc.spec.models_normalized()
        self._reconcile_models(svc, models)
        policy = svc.spec.rollout.normalized()
        desired = max(int(svc.spec.replicas), 0)
        hosts = topology.hosts_per_slice(svc.spec.tpu_policy.accelerator,
                                         svc.spec.tpu_policy.topology)
        groups = self._observed_groups(svc, hosts)
        sp.set(desired=desired, observed=len(groups))
        target_hash = image_hash(decode_variant(image, svc.spec.decode,
                                                svc.spec.sharding,
                                                pooled=bool(models)))
        new = [g for g in groups if g.hash == target_hash]
        old = [g for g in groups if g.hash != target_hash]

        # failed gangs are torn down whole (slice = one failure domain);
        # the create pass below brings the replica back
        for g in list(new):
            if g.failed:
                self._delete_group(svc, g)
                new.remove(g)

        now = self.clock()
        # 1. reap drained old replicas whose grace elapsed
        for g in list(old):
            dl = g.drain_deadline()
            if dl is not None and now >= dl:
                self._delete_group(svc, g)
                old.remove(g)

        ready_new = sum(g.ready for g in new)
        active_old = [g for g in old if not g.draining]
        ready_active_old = sum(g.ready for g in active_old)
        min_ready = max(desired - policy.max_unavailable, 0)

        # 2. drain old replicas the ready budget can spare — not-ready old
        #    gangs cost nothing to drain; ready ones only down to the floor
        for g in sorted(active_old, key=lambda g: (g.ready, g.index)):
            budget = ready_new + ready_active_old - (1 if g.ready else 0)
            if g.ready and budget < min_ready:
                break
            self._mark_draining(svc, g, now + policy.drain_seconds)
            active_old.remove(g)
            if g.ready:
                ready_active_old -= 1

        # 3. surge new replicas within the total-capacity budget; a gang
        #    that LOST a pod (deleted/evicted, not Failed) self-heals the
        #    same way — _create_group tolerates the pods that still exist
        total = len(new) + len(old)
        by_index = {g.index: g for g in new}
        for i in range(desired):
            g = by_index.get(i)
            if g is not None:
                if len(g.pods) < hosts and not g.draining:
                    self._create_group(svc, image, target_hash, i, hosts)
                continue
            if total >= desired + policy.max_surge:
                break
            self._create_group(svc, image, target_hash, i, hosts)
            total += 1

        # 4. surplus new replicas (scale-down) drain like old ones
        live_new = [g for g in new if not g.draining]
        for g in sorted(live_new, key=lambda g: -g.index):
            if len(live_new) <= desired:
                break
            self._mark_draining(svc, g, now + policy.drain_seconds)
            live_new.remove(g)
        for g in list(new):
            if g.index >= desired:
                dl = g.drain_deadline()
                if dl is not None and now >= dl:
                    self._delete_group(svc, g)
                    new.remove(g)

        res = self._update_status(svc, image, target_hash, desired, policy,
                                  new, old)
        if res.requeue_after is not None:
            # wake exactly when the earliest drain grace elapses, not a
            # full sync period later — a drained replica should be reaped
            # (and its successor surged) the moment its deadline passes
            deadlines = [d for d in (g.drain_deadline()
                                     for g in [*old, *new]) if d is not None]
            if deadlines:
                res.requeue_after = min(res.requeue_after,
                                        max(min(deadlines) - now, 0.01))
        return res

    # ---------------------------------------------------------- model pool
    def _reconcile_models(self, svc: InferenceService, models) -> None:
        """Converge ``status.models`` onto the resolved spec refs: each
        ref's image (explicit pin wins, else the named ``Model``'s
        ``latest_image``) and a coarse phase. The replica pools follow
        THIS map by weight hot-swap — resolving a new image here is the
        whole deployment action for a pooled model; no pod rolls. The
        autoscaler-owned ``slo`` sub-field of each entry is preserved,
        and removed refs drop their entries (stale budget states must
        not outlive their model)."""
        if not models and not svc.status.models:
            return
        want: Dict[str, Tuple[str, str]] = {}
        for ref in models:
            img = ref.image
            if not img and ref.model_name:
                model = self.cluster.try_get(Model, svc.metadata.namespace,
                                             ref.model_name)
                img = model.status.latest_image if model is not None else ""
            want[ref.name] = (img, "Ready" if img else "Pending")
        have = {name: (st.image, st.phase)
                for name, st in svc.status.models.items()}
        if want == have:
            return

        def mutate(s: InferenceService) -> None:
            for name in list(s.status.models):
                if name not in want:
                    del s.status.models[name]
            for name, (img, phase) in want.items():
                entry = s.status.models.get(name)
                if entry is None:
                    entry = s.status.models[name] = ModelStatus(name=name)
                entry.image = img
                entry.phase = phase
        try:
            self.cluster.update_with_retry(
                InferenceService, svc.metadata.namespace,
                svc.metadata.name, mutate, subresource="status")
        except NotFoundError:
            return
        mutate(svc)   # keep this pass's snapshot coherent
        self.cluster.record_event(
            svc, "Normal", "ModelPoolResolved",
            "model pool: " + ", ".join(
                f"{n}={img or '<pending>'}"
                for n, (img, _) in sorted(want.items())))

    # ------------------------------------------------------------- observed
    def _target_image(self, svc: InferenceService) -> str:
        if svc.spec.image:
            return svc.spec.image
        if not svc.spec.model_name:
            return ""
        model = self.cluster.try_get(Model, svc.metadata.namespace,
                                     svc.spec.model_name)
        return model.status.latest_image if model is not None else ""

    def _selector(self, svc: InferenceService) -> Dict[str, str]:
        return {constants.LABEL_INFERENCESERVICE_NAME: svc.metadata.name}

    def _observed_groups(self, svc: InferenceService,
                         hosts: int) -> List[_ReplicaGroup]:
        by_key: Dict[Tuple[str, int], _ReplicaGroup] = {}
        for pod in self.cluster.list(Pod, svc.metadata.namespace,
                                     self._selector(svc)):
            if pod.metadata.deletion_timestamp is not None:
                continue
            h = pod.metadata.labels.get(constants.LABEL_SERVING_IMAGE_HASH,
                                        "")
            try:
                idx = int(pod.metadata.labels.get(
                    constants.LABEL_SERVING_REPLICA_INDEX, "0"))
            except ValueError:
                continue
            g = by_key.setdefault((h, idx), _ReplicaGroup(h, idx, hosts))
            g.pods.append(pod)
        return sorted(by_key.values(), key=lambda g: (g.hash, g.index))

    # -------------------------------------------------------------- actions
    def _gang_name(self, svc: InferenceService, hash_: str,
                   index: int) -> str:
        return f"{svc.metadata.name}-{hash_[:6]}-r{index}"

    def _create_group(self, svc: InferenceService, image: str, hash_: str,
                      index: int, hosts: int) -> None:
        tpu = svc.spec.tpu_policy
        chips = topology.chips_per_host(tpu.accelerator)
        gang = self._gang_name(svc, hash_, index)
        serve_args = ["--serve", f"--n-slots={svc.spec.n_slots}",
                      f"--prefix-bucket-len={svc.spec.prefix_bucket_len}"]
        if svc.spec.models_normalized():
            # the mode bit only — the replica runtime builds a
            # ModelPool and follows status.models for the member list
            # (membership converges by hot-swap, never by pod args)
            serve_args.append("--model-pool")
        if svc.spec.decode is not None:
            # thread the decode policy to the replica runtime as args —
            # the serving image's declared contract, like --serve and
            # --n-slots above (the in-process plane consumes the same
            # policy through its engine factory)
            d = svc.spec.decode.normalized()
            if d.int8_weights:
                serve_args.append("--serve-int8")
            if d.draft_model:
                serve_args += [f"--spec-draft={d.draft_model}",
                               f"--spec-k={d.spec_k}"]
        if svc.spec.sharding is not None:
            s = svc.spec.sharding.normalized()
            if not s.is_trivial():
                # the replica runtime builds its serving mesh from these
                # (parallel/mesh.serving_mesh over the gang's chips)
                serve_args += [f"--mesh-data={s.data}",
                               f"--mesh-model={s.model}",
                               f"--mesh-expert={s.expert}",
                               f"--shard-rules={s.rules}"]
        for host in range(hosts):
            name = f"{gang}-h{host}" if hosts > 1 else gang
            container = Container(
                name=constants.DEFAULT_CONTAINER_NAME, image=image,
                args=list(serve_args))
            container.resources.requests[constants.RESOURCE_TPU] = chips
            container.resources.limits[constants.RESOURCE_TPU] = chips
            container.set_env(constants.ENV_PJRT_DEVICE, "TPU")
            container.set_env(constants.ENV_TPU_WORKER_ID, str(host))
            container.set_env(constants.ENV_PYTHONUNBUFFERED, "1")
            pod = Pod(
                metadata=ObjectMeta(
                    name=name, namespace=svc.metadata.namespace,
                    labels={**self._selector(svc),
                            constants.LABEL_SERVING_IMAGE_HASH: hash_,
                            constants.LABEL_SERVING_REPLICA_INDEX:
                                str(index),
                            constants.LABEL_TASK_INDEX: str(host)},
                    annotations={
                        constants.ANNOTATION_SERVING_IMAGE: image,
                        # the replica's hosts form one gang: all-or-nothing
                        # placement, exactly the slice failure domain
                        constants.ANNOTATION_GANG_GROUP_NAME: gang},
                    owner_references=[self._owner_ref(svc)]),
                spec=PodSpec(
                    restart_policy="Never",
                    node_selector={
                        constants.NODE_SELECTOR_TPU_ACCELERATOR:
                            tpu.accelerator,
                        constants.NODE_SELECTOR_TPU_TOPOLOGY: tpu.topology},
                    containers=[container]))
            try:
                self.cluster.create(pod)
            except AlreadyExistsError:
                pass
        self.cluster.record_event(
            svc, "Normal", "ReplicaCreated",
            f"created replica {gang} ({hosts} host(s)) for image {image}")

    def _mark_draining(self, svc: InferenceService, g: _ReplicaGroup,
                       deadline: float) -> None:
        if g.draining:
            return
        for pod in g.pods:
            def mutate(p: Pod) -> None:
                p.metadata.annotations[
                    constants.ANNOTATION_SERVING_DRAIN_DEADLINE] = \
                    repr(deadline)
            try:
                self.cluster.update_with_retry(
                    Pod, pod.metadata.namespace, pod.metadata.name, mutate)
            except NotFoundError:
                pass
            # keep the local snapshot coherent so later passes over the
            # same group list see the mark this pass just wrote
            mutate(pod)
        self.cluster.record_event(
            svc, "Normal", "ReplicaDraining",
            f"draining replica {self._gang_name(svc, g.hash, g.index)}")

    def _delete_group(self, svc: InferenceService, g: _ReplicaGroup) -> None:
        for pod in g.pods:
            try:
                self.cluster.delete(Pod, pod.metadata.namespace,
                                    pod.metadata.name)
            except NotFoundError:
                pass
        self.cluster.record_event(
            svc, "Normal", "ReplicaRemoved",
            f"removed replica {self._gang_name(svc, g.hash, g.index)}")

    # --------------------------------------------------------------- status
    def _update_status(self, svc: InferenceService, image: str,
                       target_hash: str, desired: int,
                       policy: RolloutPolicy, new: List[_ReplicaGroup],
                       old: List[_ReplicaGroup]) -> Result:
        live_new = [g for g in new if not g.draining]
        ready_new = sum(g.ready for g in live_new)
        ready_total = ready_new + sum(g.ready for g in old)
        # complete only once surplus (draining) replicas are reaped too —
        # declaring READY with drains outstanding would drop the requeue
        # that eventually deletes them
        complete = not old and len(new) == len(live_new) == desired \
            and ready_new >= desired
        if complete:
            phase, msg = ServicePhase.READY, f"serving {image}"
            canary = 1.0
            current = image
        else:
            phase = ServicePhase.PROGRESSING
            msg = (f"{ready_new}/{desired} replicas ready on target image"
                   + (f"; {len(old)} old-version replica(s) remain"
                      if old else ""))
            # Degraded = a fleet that HAD more ready capacity dipping below
            # the floor; an initial deployment still coming up (previous
            # ready count no higher) is just progressing
            if (ready_total < max(desired - policy.max_unavailable, 0)
                    and svc.status.ready_replicas > ready_total):
                phase = ServicePhase.DEGRADED
            canary = 0.0
            if old and ready_new:
                canary = max(policy.canary_weight,
                             min(ready_new / desired, 1.0) if desired
                             else 1.0)
            elif not old:
                # scale-up of a single version: all traffic stays on it
                canary = 1.0
            current = svc.status.current_image or \
                (old[0].pods[0].metadata.annotations.get(
                    constants.ANNOTATION_SERVING_IMAGE, "") if old
                 else image)

        want = dict(
            phase=phase, message=msg,
            current_image=image if complete else current,
            target_image=image, replicas=len(new) + len(old),
            ready_replicas=ready_total, updated_replicas=len(live_new),
            canary_weight=round(canary, 4))
        # write only on change: an unconditional status write would fire a
        # watch event that re-enqueues this very object — a self-sustaining
        # reconcile loop
        if any(getattr(svc.status, k) != v for k, v in want.items()):
            def mutate(s: InferenceService) -> None:
                for k, v in want.items():
                    setattr(s.status, k, v)
            try:
                self.cluster.update_with_retry(
                    InferenceService, svc.metadata.namespace,
                    svc.metadata.name, mutate, subresource="status")
            except NotFoundError:
                return Result()
        if complete:
            return Result()
        return Result(requeue_after=self.config.sync_period_seconds)

    def _set_status(self, svc: InferenceService, *, phase: ServicePhase,
                    message: str) -> None:
        if svc.status.phase == phase and svc.status.message == message:
            return

        def mutate(s: InferenceService) -> None:
            s.status.phase = phase
            s.status.message = message
        try:
            self.cluster.update_with_retry(
                InferenceService, svc.metadata.namespace, svc.metadata.name,
                mutate, subresource="status")
        except NotFoundError:
            pass

    def _owner_ref(self, svc: InferenceService) -> OwnerReference:
        return OwnerReference(
            api_version=svc.api_version, kind=svc.kind,
            name=svc.metadata.name, uid=svc.metadata.uid, controller=True)


def setup_inferenceservice_controller(
    cluster: InMemoryCluster,
    manager: Manager,
    config: Optional[JobControllerConfig] = None,
    clock: Callable[[], float] = time.monotonic,
    tracer=None,
) -> InferenceServiceReconciler:
    """Wire the controller: watch InferenceServices, their replica pods,
    and Models (a new ``latest_image`` is what starts a rollout)."""
    reconciler = InferenceServiceReconciler(cluster, config=config,
                                            clock=clock, tracer=tracer)
    # the workqueue shares the reconciler's clock so drain deadlines and
    # requeue delays advance together under an injected test clock
    controller = Controller("inferenceservice", reconciler.reconcile,
                            queue=Workqueue(clock=clock))
    manager.add_controller(controller)

    def on_event(event: WatchEvent) -> None:
        if event.kind == constants.KIND_INFERENCESERVICE:
            controller.enqueue(event.obj.metadata.namespace,
                               event.obj.metadata.name)
        elif event.kind == "Pod":
            owner = event.obj.metadata.labels.get(
                constants.LABEL_INFERENCESERVICE_NAME)
            if owner:
                controller.enqueue(event.obj.metadata.namespace, owner)
        elif event.kind == constants.KIND_MODEL:
            for svc in cluster.list(InferenceService,
                                    event.obj.metadata.namespace):
                if svc.spec.model_name == event.obj.metadata.name:
                    controller.enqueue(svc.metadata.namespace,
                                       svc.metadata.name)

    cluster.watch(on_event)
    return reconciler
