"""Lease-based leader election (reference main.go:77-83 — controller-runtime
leader election "torch-on-k8s-election").

A coordination Lease object in the cluster: candidates try to acquire it,
the holder renews every ``renew_seconds``, and anyone observing a lease older
than ``lease_seconds`` may take over. Conflict-safe through the cluster's
resource-version semantics — a lost update means someone else renewed first.
"""
from __future__ import annotations

import datetime as _dt
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from tpu_on_k8s.api.core import ObjectMeta, utcnow
from tpu_on_k8s.client.cluster import AlreadyExistsError, ConflictError, InMemoryCluster
from tpu_on_k8s.utils.logging import get_logger

_log = get_logger("leaderelection")

LEASE_NAME = "tpu-on-k8s-election"


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease analog.

    Internal fields are flat; the wire hooks emit/accept the real
    coordination.k8s.io shape — ``spec.holderIdentity``,
    ``spec.leaseDurationSeconds`` (integer), ``spec.renewTime`` (MicroTime:
    RFC 3339 with a *mandatory* 6-digit fraction — a real apiserver's strict
    layout parse rejects a bare seconds timestamp). Without this mapping a
    real cluster would prune the unknown flat fields and every candidate
    would see an unheld lease: split-brain. Pinned by the golden fixture in
    tests/fixtures/wire/lease_update_request.json.
    """

    api_version: str = "coordination.k8s.io/v1"
    kind: str = "Lease"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder: str = ""
    renew_time: Optional[_dt.datetime] = None
    lease_seconds: float = 15.0

    @staticmethod
    def __wire_out__(d):
        spec: dict = {}
        holder = d.pop("holder", None)
        if holder:
            spec["holderIdentity"] = holder
        rt = d.pop("renewTime", None)
        if rt:
            if "." not in rt:  # MicroTime: fraction is not optional
                # insert before any offset suffix (Z, +hh:mm, -hh:mm after
                # the date part) so non-UTC/naive clocks stay parseable too
                for i, ch in enumerate(rt[11:], start=11):
                    if ch in "Z+-":
                        rt = rt[:i] + ".000000" + rt[i:]
                        break
                else:
                    rt += ".000000"
            spec["renewTime"] = rt
        ls = d.pop("leaseSeconds", None)
        if ls is not None:
            # integer ≥ 1 on the wire (the apiserver's validation floor);
            # sub-second test leases round up rather than expiring instantly
            spec["leaseDurationSeconds"] = max(1, int(round(ls)))
        d["spec"] = spec
        return d

    @staticmethod
    def __wire_in__(d):
        spec = d.get("spec")
        if isinstance(spec, dict):
            d = dict(d)
            if "holderIdentity" in spec:
                d["holder"] = spec["holderIdentity"] or ""
            if spec.get("renewTime"):
                d["renew_time"] = spec["renewTime"]
            if spec.get("leaseDurationSeconds") is not None:
                d["lease_seconds"] = float(spec["leaseDurationSeconds"])
        return d


class LeaderElector:
    """Acquire/renew loop; ``is_leader`` gates the manager's controllers."""

    def __init__(self, cluster: InMemoryCluster, identity: str,
                 namespace: str = "tpu-on-k8s-system",
                 lease_seconds: float = 15.0, renew_seconds: float = 5.0,
                 clock: Callable[[], _dt.datetime] = utcnow,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None,
                 lease_name: str = LEASE_NAME):
        self.cluster = cluster
        self.identity = identity
        self.lease_name = lease_name
        self.namespace = namespace
        self.lease_seconds = lease_seconds
        self.renew_seconds = renew_seconds
        self.clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def is_leader(self) -> bool:
        return self._leader

    # ------------------------------------------------------------------ core
    def _expired(self, lease: Lease) -> bool:
        if lease.renew_time is None:
            return True
        age = (self.clock() - lease.renew_time).total_seconds()
        return age > lease.lease_seconds

    def try_acquire_or_renew(self) -> bool:
        """One election round; returns whether we hold the lease after it."""
        now = self.clock()
        existing = self.cluster.try_get(Lease, self.namespace, self.lease_name)
        if existing is None:
            lease = Lease(metadata=ObjectMeta(name=self.lease_name,
                                              namespace=self.namespace),
                          holder=self.identity, renew_time=now,
                          lease_seconds=self.lease_seconds)
            try:
                self.cluster.create(lease)
            except (AlreadyExistsError, ConflictError):
                return self._transition(False)
            return self._transition(True)
        if existing.holder != self.identity and not self._expired(existing):
            return self._transition(False)

        def mutate(lease: Lease) -> None:
            # re-checked under the update's conflict retry: only renew what
            # is still ours or still expired
            if lease.holder != self.identity and not self._expired(lease):
                raise _LostRace()
            lease.holder = self.identity
            lease.renew_time = self.clock()
            lease.lease_seconds = self.lease_seconds

        try:
            self.cluster.update_with_retry(Lease, self.namespace,
                                           self.lease_name, mutate)
        except _LostRace:
            return self._transition(False)
        return self._transition(True)

    def _transition(self, leading: bool) -> bool:
        if leading and not self._leader:
            self._leader = True
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self._leader:
            self._leader = False
            if self.on_stopped_leading:
                self.on_stopped_leading()
        return leading

    # --------------------------------------------------------------- run loop
    def run(self) -> None:  # pragma: no cover - timing loop; logic is above
        self.try_acquire_or_renew()  # immediate first round, then renew cycle
        while not self._stop.wait(self.renew_seconds):
            self.try_acquire_or_renew()

    def start(self) -> None:  # pragma: no cover
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._leader:
            self._release()

    def _release(self) -> None:
        def mutate(lease: Lease) -> None:
            if lease.holder == self.identity:
                lease.holder = ""
                lease.renew_time = None

        try:
            self.cluster.update_with_retry(Lease, self.namespace,
                                           self.lease_name, mutate)
        # analyze: allow[silent-loss] best-effort lease release — expiry is the fallback, and the failure is logged
        except Exception:
            # best-effort: the lease expires on its own if the release write
            # loses a race or the server is gone — but say so
            _log.warning("lease release failed; relying on expiry",
                         exc_info=True)
        self._transition(False)


class _LostRace(Exception):
    pass
