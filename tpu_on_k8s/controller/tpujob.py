"""TPUJob concrete reconciler: TPU cluster-spec wiring + job status FSM.

Analog of /root/reference/controllers/train/ — most importantly the
``SetClusterSpec`` rework (torchjob_controller.go:314-449): where the reference
injects NCCL rendezvous env (MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE), this
injects PJRT/XLA process wiring (BASELINE.json north star):

* ``PJRT_DEVICE=TPU``, ``TPU_WORKER_ID``/``TPU_PROCESS_ID`` (rank),
  ``TPU_NUM_PROCESSES`` (world size in hosts), ``XLA_COORDINATOR_ADDRESS``
  (master-0 service DNS), ``TPU_WORKER_HOSTNAMES`` (rank-ordered host DNS);
* ``google.com/tpu`` chip requests + GKE accelerator/topology nodeSelectors;
* Megascale DCN env for multi-slice jobs (``MEGASCALE_*``);
* elastic rendezvous CLI args (``--rdzv_backend=xla ...``) and the world-size
  downward-API annotation trick (torchjob_controller.go:419-439) so an in-place
  restarted container observes the post-scale world size.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import (
    Container,
    EnvVar,
    EnvVarSource,
    Pod,
    PodPhase,
    Volume,
    VolumeMount,
)
from tpu_on_k8s.api.defaults import set_defaults_tpujob
from tpu_on_k8s.api.types import TaskType, TPUJob, JobConditionType
from tpu_on_k8s.client.cluster import InMemoryCluster, WatchEvent
from tpu_on_k8s.controller.config import JobControllerConfig
from tpu_on_k8s.controller.engine import JobEngine
from tpu_on_k8s.controller.runtime import Controller, Manager, Request, Result
from tpu_on_k8s.features import FeatureGates, features
from tpu_on_k8s.gang import topology
from tpu_on_k8s.metrics import JobMetrics
from tpu_on_k8s.utils import conditions
from tpu_on_k8s.api.core import utcnow


class TPUJobHooks:
    """WorkloadHooks implementation for TPUJob (the ControllerInterface impl,
    torchjob_controller.go:117-210 + train/{job,pod,service}.go)."""

    def __init__(self, config: JobControllerConfig, gates: FeatureGates,
                 metrics: JobMetrics, restarter=None) -> None:
        self.config = config
        self.gates = gates
        self.metrics = metrics
        self.restarter = restarter

    # ---- identity / ordering --------------------------------------------------
    def task_order(self, job: TPUJob) -> List[TaskType]:
        """AIMaster first, then Master, then Worker
        (GetTaskReconcilerOrders, torchjob_controller.go:464-471)."""
        return [t for t in (TaskType.AIMASTER, TaskType.MASTER, TaskType.WORKER)
                if t in job.spec.tasks]

    def is_master(self, task_type: TaskType, index: int) -> bool:
        return task_type == TaskType.MASTER and index == 0

    def needs_service(self, job: TPUJob, task_type: TaskType) -> bool:
        # Every slice host gets stable DNS (workers included — their hostnames
        # feed TPU_WORKER_HOSTNAMES); AIMaster is reached via the job API only.
        return task_type in (TaskType.MASTER, TaskType.WORKER)

    def enable_elastic_scaling(self, job: TPUJob) -> bool:
        """Annotation-gated (reference elastic_scale.go:81-83) — and native
        elastic jobs (elastic_policy set) get the same machinery: generation
        labels, preempt protection, and the scale workflow execute their
        autoscaler-driven spec changes."""
        if job.spec.elastic_policy is not None:
            return True
        return (
            job.metadata.annotations.get(constants.ANNOTATION_ENABLE_ELASTIC, "")
            .lower() == "true"
        )

    def failover_action(self, job: TPUJob, pod: Pod) -> str:
        # In-place restart preserves the TPU slice binding (no re-schedule), so
        # elastic jobs prefer it when a CRR executor exists (SURVEY §5.3).
        if self.enable_elastic_scaling(job) and self.restarter is not None:
            return "inplace"
        return "recreate"

    # ---- the TPU cluster-spec wiring -----------------------------------------
    @staticmethod
    def _world(job: TPUJob) -> Dict[TaskType, int]:
        """Host counts by type, excluding AIMaster (not part of the XLA world —
        reference excludes it from WORLD_SIZE, torchjob_controller.go:441-444)."""
        return {
            tt: spec.num_tasks
            for tt, spec in job.spec.tasks.items()
            if tt is not TaskType.AIMASTER
        }

    def _rank(self, job: TPUJob, task_type: TaskType, index: int) -> int:
        """Master is rank 0; workers shift by the master count
        (torchjob_controller.go:347)."""
        if task_type == TaskType.MASTER:
            return index
        masters = job.spec.tasks.get(TaskType.MASTER)
        return index + (masters.num_tasks if masters else 0)

    def _coordinator_address(self, job: TPUJob, port: int) -> str:
        lead = (TaskType.MASTER if TaskType.MASTER in job.spec.tasks else TaskType.WORKER)
        name = conditions.gen_general_name(job.metadata.name, lead, 0)
        return f"{name}.{job.metadata.namespace}:{port}"

    def _hostnames(self, job: TPUJob) -> List[str]:
        out = []
        for tt in (TaskType.MASTER, TaskType.WORKER):
            spec = job.spec.tasks.get(tt)
            if spec is None:
                continue
            for i in range(spec.num_tasks):
                out.append(conditions.gen_general_name(job.metadata.name, tt, i))
        return out

    def set_cluster_spec(self, job: TPUJob, pod: Pod, task_type: TaskType, index: int) -> None:
        port = self._port_from_job(job)
        elastic = self.enable_elastic_scaling(job)
        world = sum(self._world(job).values())
        rank = self._rank(job, task_type, index)
        tpu = job.spec.tpu_policy

        if task_type is not TaskType.AIMASTER:
            # GKE TPU scheduling surface: slice nodeSelectors + chip requests.
            # Overwrite, not setdefault: elastic respec re-applies this to
            # live pods and the selectors must track the current slice shape.
            pod.spec.node_selector[constants.NODE_SELECTOR_TPU_ACCELERATOR] = tpu.accelerator
            pod.spec.node_selector[constants.NODE_SELECTOR_TPU_TOPOLOGY] = tpu.topology
            chips = topology.chips_per_host(tpu.accelerator)
            for c in pod.spec.containers:
                c.resources.requests.setdefault(constants.RESOURCE_TPU, chips)
                c.resources.limits.setdefault(constants.RESOURCE_TPU, chips)
            self._inject_perf_env(pod)

        coordinator = self._coordinator_address(job, port)
        if (task_type == TaskType.MASTER and index == 0
                and self.gates.enabled(features.LOCAL_MASTER_ADDR)):
            # Master talks to itself without a DNS round-trip
            # (TorchLocalMasterAddr analog, torchjob_controller.go:338-345).
            coordinator = f"localhost:{port}"

        for container in pod.spec.containers:
            env = container.set_env
            env(constants.ENV_PJRT_DEVICE, "TPU")
            env(constants.ENV_COORDINATOR_ADDRESS, coordinator)
            env(constants.ENV_TPU_WORKER_ID, str(rank))
            env(constants.ENV_PROCESS_ID, str(rank))
            env(constants.ENV_TPU_WORKER_HOSTNAMES, ",".join(self._hostnames(job)))
            env(constants.ENV_PYTHONUNBUFFERED, "1")
            if elastic:
                # World size flows through an annotation + downward API so an
                # in-place restart picks up the new value without re-creating
                # the pod (torchjob_controller.go:419-439).
                pod.metadata.annotations.setdefault(
                    constants.ANNOTATION_WORLD_SIZE, str(world))
                # set_env (replace-in-place) keeps re-application idempotent —
                # elastic respec re-runs this on live pods.
                container.set_env(
                    constants.ENV_NUM_PROCESSES, "",
                    EnvVarSource(
                        field_path=f"metadata.annotations['{constants.ANNOTATION_WORLD_SIZE}']"))
            else:
                env(constants.ENV_NUM_PROCESSES, str(world))
            if tpu.num_slices > 1:
                hosts_per = topology.hosts_per_slice(tpu.accelerator, tpu.topology)
                # Workers tile the slices (the gang quorum is worker-only, so
                # worker index — not the master-shifted rank — picks the
                # slice); master/AIMaster coordinate from slice 0.
                slice_id = (index // max(hosts_per, 1)
                            if task_type == TaskType.WORKER else 0)
                env(constants.ENV_MEGASCALE_COORDINATOR, self._coordinator_address(job, port))
                env(constants.ENV_MEGASCALE_NUM_SLICES, str(tpu.num_slices))
                env(constants.ENV_MEGASCALE_SLICE_ID, str(slice_id))

        ep = job.spec.elastic_policy
        if ep is not None and task_type in (TaskType.MASTER, TaskType.WORKER):
            # Elastic rendezvous CLI args prepended to user args
            # (torchjob_controller.go:385-417).
            main = pod.spec.default_container()
            if main is not None:
                endpoint = ep.rendezvous_endpoint or coordinator
                rdzv = [
                    f"{constants.ARG_RDZV_BACKEND}={ep.rendezvous_backend}",
                    f"{constants.ARG_RDZV_ENDPOINT}={endpoint}",
                    f"{constants.ARG_RDZV_ID}={job.metadata.name}",
                    f"{constants.ARG_NPROC_PER_NODE}={ep.nproc_per_node}",
                    f"{constants.ARG_NNODES}={ep.min_replicas}:{ep.max_replicas}",
                ]
                existing = set(a.split("=")[0] for a in main.args)
                main.args = [a for a in rdzv if a.split("=")[0] not in existing] + main.args
            if task_type == TaskType.WORKER:
                self._add_elastic_init_containers(job, pod, coordinator)

    def _inject_perf_env(self, pod: Pod) -> None:
        """Persistent-compile-cache + latency-hiding wiring for slice hosts
        (consumed by `tpu_on_k8s/train/compile.py`): a node-local hostPath
        volume mounted into every container plus ``JAX_COMPILATION_CACHE_DIR``
        pointing at it, so a restarted/failed-over pod on the same node finds
        the previous incarnation's compiled programs (content-addressed —
        every slice host compiles the identical SPMD program, so the cache
        warms once per node, ever); and the async-collective
        ``LIBTPU_INIT_ARGS`` set. Setdefault semantics throughout: values the
        user set in the pod template always win, and re-application during
        elastic respec stays idempotent."""
        if not any(v.name == constants.COMPILE_CACHE_VOLUME
                   for v in pod.spec.volumes):
            pod.spec.volumes.append(Volume(
                name=constants.COMPILE_CACHE_VOLUME,
                host_path=constants.DEFAULT_COMPILE_CACHE_DIR))
        for container in pod.spec.containers:
            if not any(m.name == constants.COMPILE_CACHE_VOLUME
                       for m in container.volume_mounts):
                container.volume_mounts.append(VolumeMount(
                    name=constants.COMPILE_CACHE_VOLUME,
                    mount_path=constants.DEFAULT_COMPILE_CACHE_DIR))
            env = container.env_map()
            if constants.ENV_JAX_COMPILATION_CACHE_DIR not in env:
                container.set_env(constants.ENV_JAX_COMPILATION_CACHE_DIR,
                                  constants.DEFAULT_COMPILE_CACHE_DIR)
            if constants.ENV_LIBTPU_INIT_ARGS not in env:
                container.set_env(constants.ENV_LIBTPU_INIT_ARGS,
                                  constants.LIBTPU_PERF_ARGS)
            # profiling hooks (`utils/profiling.py` via `train/loop.py`):
            # only when the operator asked — both default off, and user
            # pod-template values still win
            if (self.config.profile_dir
                    and constants.ENV_PROFILE_DIR not in env):
                container.set_env(constants.ENV_PROFILE_DIR,
                                  self.config.profile_dir)
            if (self.config.profiler_port
                    and constants.ENV_PROFILER_PORT not in env):
                container.set_env(constants.ENV_PROFILER_PORT,
                                  str(self.config.profiler_port))

    def _add_elastic_init_containers(self, job: TPUJob, pod: Pod, coordinator: str) -> None:
        """Image-warmup + master-waiter init containers for elastic workers
        (reference elastic_scale.go:549-654)."""
        have = {c.name for c in pod.spec.init_containers}
        main = pod.spec.containers[0] if pod.spec.containers else None
        if "image-warmup" not in have and main is not None:
            pod.spec.init_containers.append(Container(
                name="image-warmup", image=main.image, command=["sh", "-c", "true"]))
        if "master-waiter" not in have:
            host = coordinator.rsplit(":", 1)[0]
            pod.spec.init_containers.append(Container(
                name="master-waiter", image="busybox:1.36",
                command=["sh", "-c",
                         f"until nslookup {host}; do sleep 1; done"]))

    @staticmethod
    def _port_from_job(job: TPUJob) -> int:
        """Coordinator port from the lead task's declared container port
        (getPortFromJob, torchjob_controller.go:508-521)."""
        for tt in (TaskType.MASTER, TaskType.WORKER):
            task = job.spec.tasks.get(tt)
            if task is not None:
                return task.template.spec.coordinator_port()
        return constants.DEFAULT_COORDINATOR_PORT

    # ---- status FSM -----------------------------------------------------------
    def update_job_status(self, job: TPUJob, pods_by_type: Dict[TaskType, List[Pod]]) -> None:
        """Reference updateGeneralJobStatus (train/job.go:100-207): Running when
        the master runs; Succeeded when master succeeded and workers drained;
        Failed on permanent pod failures (restartable failures were already
        failed-over by reconcile_one_pod and marked Restarting)."""
        statuses = job.status.task_statuses
        world_types = [tt for tt in (TaskType.MASTER, TaskType.WORKER) if tt in job.spec.tasks]
        if not world_types:
            return

        from tpu_on_k8s.api.types import ReplicaStatus
        total_failed = sum((statuses.get(tt) or ReplicaStatus()).failed
                           for tt in world_types)
        if total_failed > 0:
            conditions.update_job_conditions(
                job.status, JobConditionType.FAILED, "PodFailed",
                f"{total_failed} task pod(s) failed permanently")
            job.status.completion_time = job.status.completion_time or utcnow()
            self.metrics.failure()
            return

        # While a failover is in flight, Restarting holds until the job is
        # fully re-assembled (all world replicas ready) — only then does
        # Running demote it (Running/Restarting mutual exclusion, reference
        # pkg/utils/utils.go:201-223).
        restarting = conditions.has_condition(job.status, JobConditionType.RESTARTING)
        total_expected = sum(job.spec.tasks[tt].num_tasks for tt in world_types)
        total_ready = sum((statuses.get(tt) or ReplicaStatus()).ready for tt in world_types)
        can_mark_running = (not restarting) or total_ready >= total_expected

        if TaskType.MASTER in job.spec.tasks:
            master = statuses.get(TaskType.MASTER)
            n_master = job.spec.tasks[TaskType.MASTER].num_tasks
            if master is None:
                return
            if master.succeeded >= n_master:
                workers = statuses.get(TaskType.WORKER)
                workers_active = workers.active if workers else 0
                if workers_active == 0:
                    conditions.update_job_conditions(
                        job.status, JobConditionType.SUCCEEDED, "JobSucceeded",
                        "master completed and workers drained")
                    job.status.completion_time = job.status.completion_time or utcnow()
                    self.metrics.success()
                    return
            if master.active > 0 and can_mark_running:
                conditions.update_job_conditions(
                    job.status, JobConditionType.RUNNING, "JobRunning", "")
            return

        # Worker-only job.
        workers = statuses.get(TaskType.WORKER)
        if workers is None:
            return
        n_workers = job.spec.tasks[TaskType.WORKER].num_tasks
        if workers.succeeded >= n_workers:
            conditions.update_job_conditions(
                job.status, JobConditionType.SUCCEEDED, "JobSucceeded",
                "all workers succeeded")
            job.status.completion_time = job.status.completion_time or utcnow()
            self.metrics.success()
        elif workers.active > 0 and can_mark_running:
            conditions.update_job_conditions(
                job.status, JobConditionType.RUNNING, "JobRunning", "")


def submit_job(cluster: InMemoryCluster, job: TPUJob) -> TPUJob:
    """Admission path: defaulting + slice validation before the object lands in
    the store (the reference runs scheme defaulters in its create handler,
    eventhandler.go:38-64; slice validation is TPU-specific admission)."""
    set_defaults_tpujob(job)
    topology.validate_slice(job.spec.tpu_policy.accelerator, job.spec.tpu_policy.topology)
    conditions.mark_created(job)
    return cluster.create(job)


def setup_tpujob_controller(
    cluster: InMemoryCluster,
    manager: Manager,
    config: Optional[JobControllerConfig] = None,
    gates: Optional[FeatureGates] = None,
    gang_scheduler=None,
    restarter=None,
    metrics: Optional[JobMetrics] = None,
    coordinator=None,
    elastic_controller=None,
) -> JobEngine:
    """Wire the TPUJob controller into a manager: engine, watches, event
    handlers (reference SetupWithManager, torchjob_controller.go:60-115, and
    OnOwnerCreate/Update/Delete, controllers/common/eventhandler.go)."""
    config = config or JobControllerConfig()
    gates = gates or FeatureGates()
    metrics = metrics or JobMetrics()
    hooks = TPUJobHooks(config, gates, metrics, restarter=restarter)
    if elastic_controller is not None and getattr(elastic_controller, "hooks", None) is None:
        # The elastic respec path re-applies the cluster-spec wiring to live
        # pods before in-place restarts.
        elastic_controller.hooks = hooks
    engine = JobEngine(
        cluster, hooks, config=config, gang_scheduler=gang_scheduler,
        restarter=restarter, metrics=metrics, gates=gates,
        elastic_controller=elastic_controller,
    )
    controller = Controller("tpujob", engine.reconcile)
    manager.add_controller(controller)

    use_coordinator = coordinator is not None and gates.enabled(features.JOB_COORDINATOR)

    def on_event(event: WatchEvent) -> None:
        if event.kind == constants.KIND_TPUJOB:
            ns, name = event.obj.metadata.namespace, event.obj.metadata.name
            if event.type == "ADDED":
                metrics.created()
                if use_coordinator and conditions.needs_coordinator_enqueue(event.obj.status):
                    coordinator.enqueue_or_update(event.obj, controller)
                    return
                controller.enqueue(ns, name)
            elif event.type == "MODIFIED":
                if use_coordinator and coordinator.is_queuing(event.obj.metadata.uid):
                    coordinator.enqueue_or_update(event.obj, controller)
                    return
                if use_coordinator:
                    # Quota reservations drop once the job's usage is real
                    # (reference quota.go:256-277 assumed-quota expiry).
                    coordinator.observe_job_left_queued_state(event.obj)
                controller.enqueue(ns, name)
            elif event.type == "DELETED":
                engine.forget_job(f"{ns}/{name}")
                engine.release_preempt_finalizers(event.obj)
                if use_coordinator:
                    coordinator.dequeue(event.obj, reason="deleted")
                metrics.deleted()
        elif event.kind in ("Pod", "Service"):
            engine.observe_event(controller.enqueue, event)
        elif event.kind == "ContainerRecreateRequest":
            # The node agent's phase updates advance the level-triggered
            # in-place-restart protocol: requeue the owning job (the
            # restarter stamps the job label when posting) so settlement is
            # event-driven, not resync-bound.
            job_name = event.obj.metadata.labels.get(
                constants.LABEL_JOB_NAME, "")
            if job_name:
                controller.enqueue(event.obj.metadata.namespace, job_name)

    cluster.watch(on_event)
    return engine
