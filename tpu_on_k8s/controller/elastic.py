"""AIMaster-driven elastic scaling: the generation / 2-phase-checkpoint protocol.

Analog of /root/reference/controllers/train/elastic_scale.go (SURVEY §3.3) —
the multi-actor state machine between the controller, an in-cluster AIMaster,
and the training processes, driven entirely by annotations:

1. **Victim detection** — a pod with a deletionTimestamp still carrying the
   ``preempt-protector`` finalizer is being preempted but is held alive
   (elastic_scale.go:737-740).
2. **Checkpoint request** — the controller stamps
   ``ckpt-requested-version = <job generation>``; the AIMaster observes it,
   checkpoints training state to the model volume, then writes
   ``ckpt-completed-version`` (elastic_scale.go:469-488).
3. **Victim cleanup + respec** — on completion the controller drains victim
   finalizers, deletes them, and re-specs the job to the surviving capacity
   (elastic_scale.go:491-546). TPU twist: the new worker count must land on a
   slice-legal host quantum, so the respec rewrites topology/num_slices too
   (``apply_host_count`` — the reference's free-form replica arithmetic is
   illegal here, SURVEY §7).
4. **Scale workflow** — the spec change bumps ``metadata.generation``; pods
   whose generation label lags are *stale* and get the world-size annotation
   patch + in-place restart (master first, then workers —
   elastic_scale.go:210-297, restartPodInKruiseProtocol :342-397); missing
   indices are created by the engine with the new generation label; the
   ``ready-to-start-worker`` / ``scale-state`` gates sequence it all.

Unlike the reference there is no stale-service refresh step: services select
on task labels only (never generation), so DNS stays valid across restarts by
construction (the refreshStaleService dance at elastic_scale.go:402-424 is
designed out).
"""
from __future__ import annotations

from typing import List, Optional

from tpu_on_k8s.api import constants
from tpu_on_k8s.api.core import Pod, PodPhase
from tpu_on_k8s.api.types import TaskType, TPUJob
from tpu_on_k8s.client.cluster import InMemoryCluster, NotFoundError
from tpu_on_k8s.controller import failover
from tpu_on_k8s.controller.config import JobControllerConfig
from tpu_on_k8s.controller.runtime import Result
from tpu_on_k8s.gang import topology


def apply_host_count(job: TPUJob, desired_hosts: int) -> int:
    """Re-spec the job's worker group to ``desired_hosts``, snapped DOWN to a
    slice-legal quantum, honoring elastic min/max. Mutates spec in place
    (callers persist via the cluster so generation bumps). Returns the host
    count actually applied.

    Multi-slice jobs scale by dropping/adding whole slices; single-slice jobs
    rewrite the topology to the legal shape matching the new host count.
    """
    tpu = job.spec.tpu_policy
    task = job.spec.tasks.get(TaskType.WORKER)
    if task is None:
        return 0
    ep = job.spec.elastic_policy
    lo = ep.min_replicas if ep is not None else 1
    hi = ep.max_replicas if ep is not None else desired_hosts
    desired = max(lo, min(desired_hosts, max(hi, lo)))

    per_slice = topology.hosts_per_slice(tpu.accelerator, tpu.topology)
    legal = topology.legal_host_counts(tpu.accelerator)
    if tpu.num_slices > 1 and desired >= per_slice:
        # Slice-granular: whole slices over DCN. Floor division snaps DOWN;
        # the elastic floor may force a snap back up to cover min_replicas.
        new_slices = max(1, desired // per_slice)
        if new_slices * per_slice < lo:
            new_slices = -(-lo // per_slice)  # ceil
        applied = new_slices * per_slice
        tpu.num_slices = new_slices
    elif desired > max(legal):
        # Single slice maxed out: go multi-slice on the current shape.
        new_slices = max(1, desired // per_slice)
        applied = new_slices * per_slice
        tpu.num_slices = new_slices
    else:
        # Within one slice's reach (even if currently multi-slice): prefer a
        # single slice with the legal topology ≤ desired — all collectives
        # stay on ICI instead of DCN. Snapped up to the smallest legal count
        # covering min_replicas when the floor demands it.
        applied = max((c for c in legal if lo <= c <= desired), default=None)
        if applied is None:
            applied = min((c for c in legal if c >= lo), default=legal[-1])
        tpu.topology = topology.topology_for_hosts(tpu.accelerator, applied)
        tpu.num_slices = 1
    task.num_tasks = applied
    return applied


class ElasticController:
    """The engine's elastic seam (ElasticScaling contract,
    controllers/common/interface.go:83-97). ``reconcile`` returns a Result to
    short-circuit the engine (protocol in flight) or None to let the normal
    pod/service reconciliation proceed."""

    def __init__(
        self,
        cluster: InMemoryCluster,
        restarter: Optional[failover.InPlaceRestarter] = None,
        config: Optional[JobControllerConfig] = None,
        hooks=None,  # WorkloadHooks; wired by setup_tpujob_controller
    ) -> None:
        self.cluster = cluster
        self.restarter = restarter
        self.config = config or JobControllerConfig()
        self.hooks = hooks
        # live-reshard hold bookkeeping: ("ns/name", requested generation)
        # -> reconcile passes spent holding for the pod's ack. In-memory
        # (a controller restart restarts the wait, which is safe — the
        # bound is a dead-agent safety valve, not a deadline contract);
        # an annotation-based count would self-trigger reconciles on
        # every increment and burn the budget in one watch storm.
        self._reshard_holds: dict = {}

    # --------------------------------------------------------------- utilities
    @staticmethod
    def victim_pods(pods: List[Pod]) -> List[Pod]:
        """filterVictimPods (elastic_scale.go:594-602,737-740)."""
        return [
            p for p in pods
            if p.metadata.deletion_timestamp is not None
            and constants.FINALIZER_PREEMPT_PROTECTOR in p.metadata.finalizers
        ]

    @staticmethod
    def pod_generation(pod: Pod) -> int:
        try:
            return int(pod.metadata.labels.get(constants.LABEL_JOB_GENERATION, "0"))
        except ValueError:
            return 0

    @staticmethod
    def _ann_int(job: TPUJob, key: str) -> Optional[int]:
        raw = job.metadata.annotations.get(key)
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError:
            return None

    def _patch_job_annotations(self, job: TPUJob, annotations) -> None:
        try:
            updated = self.cluster.patch_meta(
                TPUJob, job.metadata.namespace, job.metadata.name,
                annotations=annotations)
            job.metadata.annotations = updated.metadata.annotations
            job.metadata.resource_version = updated.metadata.resource_version
        except NotFoundError:
            pass

    # -------------------------------------------------------------- reconcile
    def reconcile(self, job: TPUJob, pods: List[Pod]) -> Optional[Result]:
        victims = self.victim_pods(pods)
        if victims:
            return self._handle_preemption(job, pods, victims)

        stale = [p for p in pods if self.pod_generation(p) < job.metadata.generation]
        if stale:
            return self._scale(job, pods, stale)

        ann = job.metadata.annotations
        if ann.get(constants.ANNOTATION_SCALE_STATE) == constants.SCALE_STATE_INFLIGHT:
            # All pods current → the scale transaction is complete
            # (elastic_scale.go:280-294).
            self._patch_job_annotations(job, {
                constants.ANNOTATION_SCALE_STATE: constants.SCALE_STATE_DONE,
                constants.ANNOTATION_READY_TO_START_WORKER: None,
            })
            self.cluster.record_event(job, "Normal", "ScaleSucceeded",
                                      f"scale to generation {job.metadata.generation} complete")
        return None

    # ----------------------------------------------- preemption → checkpoint
    def _handle_preemption(self, job: TPUJob, pods: List[Pod],
                           victims: List[Pod]) -> Result:
        """Steps 2-3 of the protocol (TriggerCheckpointIfNecessary,
        elastic_scale.go:132-196)."""
        gen = job.metadata.generation
        requested = self._ann_int(job, constants.ANNOTATION_CKPT_REQUESTED_VERSION)
        completed = self._ann_int(job, constants.ANNOTATION_CKPT_COMPLETED_VERSION)

        if requested is None or requested < gen:
            self._patch_job_annotations(
                job, {constants.ANNOTATION_CKPT_REQUESTED_VERSION: str(gen)})
            self.cluster.record_event(
                job, "Normal", "CheckpointRequested",
                f"{len(victims)} pod(s) being preempted; requested checkpoint "
                f"at generation {gen}")
            return Result(requeue_after=self.config.sync_period_seconds)

        if completed is None or completed < requested:
            # AIMaster still checkpointing — hold the world steady.
            return Result(requeue_after=self.config.sync_period_seconds)

        # Checkpoint done: drain victims (cleanupVictimPods :491-515)...
        for pod in victims:
            try:
                self.cluster.patch_meta(
                    Pod, pod.metadata.namespace, pod.metadata.name,
                    remove_finalizers=[constants.FINALIZER_PREEMPT_PROTECTOR])
            except NotFoundError:
                pass
        # ...and re-spec to surviving capacity, snapped to a legal quantum
        # (increaseGenerationAndMarkAsSucceeded :519-546 — here the generation
        # bump is the honest k8s one: a spec change).
        victim_names = {p.metadata.name for p in victims}
        surviving_workers = sum(
            1 for p in pods
            if p.metadata.labels.get(constants.LABEL_TASK_TYPE) == TaskType.WORKER.value.lower()
            and p.metadata.name not in victim_names)

        def mutate(j: TPUJob) -> None:
            apply_host_count(j, surviving_workers)

        try:
            self.cluster.update_with_retry(
                TPUJob, job.metadata.namespace, job.metadata.name, mutate)
        except NotFoundError:
            return Result()
        self._patch_job_annotations(job, {
            constants.ANNOTATION_READY_TO_START_WORKER: "true",
        })
        self.cluster.record_event(job, "Normal", "VictimsCleaned",
                                  f"cleaned {len(victims)} victim pod(s) after checkpoint")
        return Result(requeue_after=0.0)

    # ------------------------------------------------------------------ scale
    def _scale(self, job: TPUJob, pods: List[Pod], stale: List[Pod]) -> Optional[Result]:
        """Step 4: the scale workflow (scale(), elastic_scale.go:210-297)."""
        ann = job.metadata.annotations
        outcome = self._adopt_live_reshard(job, stale)
        if outcome is not None:
            return outcome
        ready = ann.get(constants.ANNOTATION_READY_TO_START_WORKER) == "true"
        immediate = ann.get(constants.ANNOTATION_IMMEDIATELY_START_WORKER) == "true"
        ckpt_requested = self._ann_int(job, constants.ANNOTATION_CKPT_REQUESTED_VERSION)
        if ckpt_requested is not None and not (ready or immediate):
            # A checkpoint round exists for this job: wait for the AIMaster's
            # go-ahead before restarting the world (elastic_scale.go:222-225).
            return Result(requeue_after=self.config.sync_period_seconds)

        self._patch_job_annotations(job, {
            constants.ANNOTATION_SCALE_STATE: constants.SCALE_STATE_INFLIGHT})

        world = sum(t.num_tasks for tt, t in job.spec.tasks.items()
                    if tt is not TaskType.AIMASTER)

        def order(pod: Pod) -> int:
            # Master restarts before workers (elastic_scale.go:242-277).
            return 0 if pod.metadata.labels.get(
                constants.LABEL_TASK_TYPE) == TaskType.MASTER.value.lower() else 1

        ordered = sorted(stale, key=order)
        masters = [p for p in ordered if order(p) == 0]
        workers = [p for p in ordered if order(p) == 1]
        # Master-first barrier (elastic_scale.go:242-277): workers only
        # restart once every master's restart has SETTLED. With the
        # level-triggered CRR protocol a restart may be pending across
        # passes, so the barrier is a requeue, not an in-pass wait — the
        # reconcile never blocks on a node agent.
        settled = [self._restart_stale_pod(job, p, world) for p in masters]
        if not all(settled):
            return Result(requeue_after=self.config.sync_period_seconds)
        pending = sum(not self._restart_stale_pod(job, p, world)
                      for p in workers)
        if pending:
            return Result(requeue_after=self.config.sync_period_seconds)
        # Fall through to the engine: it creates missing indices with the new
        # generation label and prunes out-of-range ones.
        return None

    def _adopt_live_reshard(self, job: TPUJob,
                            stale: List[Pod]) -> Optional[Result]:
        """The live-rescale seam (`tpu_on_k8s/parallel/reshard.py`): when
        the autoscaler delivered this generation's rescale as a reshard
        REQUEST, the running pods transform their training state in
        place instead of being restarted. While the transform is pending
        the world is held steady (a restart now would race the
        transform); once the pod acks (``reshard-completed-spec`` >= the
        requested generation) the in-range pods are ADOPTED at the new
        generation — no delete, no in-place restart, no recompile — and
        only out-of-range pods (scale-in victims) are removed. A failed
        transform clears the request (``ReshardAgent.on_failed``), which
        releases the hold and lets the cold checkpoint-restart path run.
        Returns None when no live reshard is in play."""
        raw = job.metadata.annotations.get(
            constants.ANNOTATION_RESHARD_REQUESTED_SPEC)
        if raw is None:
            return None
        parsed = topology.parse_reshard_spec(raw)
        if parsed is None or parsed[0] < job.metadata.generation:
            # malformed or stale request (a later spec change superseded
            # it): the cold path is in charge
            return None
        key = (f"{job.metadata.namespace}/{job.metadata.name}", parsed[0])
        completed = self._ann_int(
            job, constants.ANNOTATION_RESHARD_COMPLETED_SPEC)
        if completed is None or completed < parsed[0]:
            # the hold is BOUNDED: an agent that died mid-transform
            # (without reaching on_failed's clear) must not wedge the
            # job forever — count held reconcile passes and past the
            # bound withdraw the request so the cold path runs
            held = self._reshard_holds.get(key, 0)
            if held >= self.config.reshard_hold_max_passes:
                self._reshard_holds.pop(key, None)
                self._patch_job_annotations(job, {
                    constants.ANNOTATION_RESHARD_REQUESTED_SPEC: None})
                self.cluster.record_event(
                    job, "Warning", "LiveReshardTimedOut",
                    f"no reshard ack after {held} held passes; falling "
                    f"back to checkpoint-restart")
                return None
            self._reshard_holds[key] = held + 1
            return Result(requeue_after=self.config.sync_period_seconds)
        self._reshard_holds.pop(key, None)
        gen = str(job.metadata.generation)
        adopted = 0
        for pod in stale:
            if self._in_range(job, pod):
                self._mark_current(pod, gen)
                adopted += 1
            else:
                # scale-in: out-of-range pods still go away — the live
                # transform only saves the SURVIVORS from a restart
                try:
                    self.cluster.patch_meta(
                        Pod, pod.metadata.namespace, pod.metadata.name,
                        remove_finalizers=[
                            constants.FINALIZER_PREEMPT_PROTECTOR])
                    self.cluster.delete(Pod, pod.metadata.namespace,
                                        pod.metadata.name)
                except NotFoundError:
                    pass
        self.cluster.record_event(
            job, "Normal", "LiveReshardAdopted",
            f"adopted {adopted} running pod(s) at generation {gen} after "
            f"live reshard — no restart")
        return Result(requeue_after=0.0)

    def _restart_stale_pod(self, job: TPUJob, pod: Pod, world: int) -> bool:
        """restartStalePod → restartPodInKruiseProtocol
        (elastic_scale.go:303-397): refresh the pod's cluster spec (world-size
        annotation via downward API, hostnames/Megascale env) FIRST, then
        restart in place. Returns True when the pod has SETTLED (restarted,
        recreated, or vanished) and False while a CRR is still in flight —
        the pod's generation label only advances on settle, so staleness
        itself re-drives the protocol next pass.

        TPU twist: if the re-spec changed the pod's slice shape (topology
        nodeSelector differs), in-place restart is impossible — the pod must
        land on a different node pool — so it is recreated instead."""
        if not self._in_range(job, pod):
            # Out-of-range stale pod (scale-in): delete; engine prunes anyway,
            # but doing it here keeps ordering master-first.
            try:
                self.cluster.patch_meta(
                    Pod, pod.metadata.namespace, pod.metadata.name,
                    remove_finalizers=[constants.FINALIZER_PREEMPT_PROTECTOR])
                self.cluster.delete(Pod, pod.metadata.namespace, pod.metadata.name)
            except NotFoundError:
                pass
            return True

        live = self.cluster.try_get(Pod, pod.metadata.namespace, pod.metadata.name)
        if live is None:
            return True
        pod_topo = live.spec.node_selector.get(constants.NODE_SELECTOR_TPU_TOPOLOGY)
        if pod_topo is not None and pod_topo != job.spec.tpu_policy.topology:
            # Slice shape changed: the node pool is wrong — recreate.
            failover.failover_recreate(self.cluster, live)
            return True

        task_type, index = self._task_identity(live)
        gen = str(job.metadata.generation)

        def mutate(p: Pod) -> None:
            p.metadata.annotations[constants.ANNOTATION_WORLD_SIZE] = str(world)
            p.metadata.annotations[constants.ANNOTATION_RESPEC_GENERATION] = gen
            if self.hooks is not None and task_type is not None:
                # Recompute the full PJRT/XLA wiring (TPU_WORKER_HOSTNAMES,
                # Megascale env) for the post-scale world — an in-place
                # restart with pre-scale hostnames would target DNS names the
                # respec just deleted.
                self.hooks.set_cluster_spec(job, p, task_type, index)

        if live.metadata.annotations.get(
                constants.ANNOTATION_RESPEC_GENERATION) != gen:
            try:
                self.cluster.update_with_retry(
                    Pod, pod.metadata.namespace, pod.metadata.name, mutate)
            except NotFoundError:
                return True
            live = self.cluster.try_get(
                Pod, pod.metadata.namespace, pod.metadata.name)
            if live is None:
                return True
        if live.status.phase != PodPhase.RUNNING:
            # Not running ⇒ nothing to restart in place: the refreshed spec
            # takes effect when the pod (re)starts. Mark it current.
            self._mark_current(pod, gen)
            return True
        outcome = failover.failover_inplace_restart(
            self.cluster, live, self.restarter)
        if outcome is failover.RestartOutcome.PENDING:
            return False
        if outcome is failover.RestartOutcome.RESTARTED:
            # Count the healthy restart ONLY once it actually happened —
            # stamping it earlier would mask a later genuine failure from
            # the backoff limit. The generation label advances with it: the
            # pod is only "current" once it runs the post-scale world.
            prev = int(live.metadata.annotations.get(
                constants.ANNOTATION_ELASTIC_RESTARTS, "0") or 0)
            self._mark_current(
                pod, gen,
                annotations={
                    constants.ANNOTATION_ELASTIC_RESTARTS: str(prev + 1)})
        # FAILED ⇒ the fallback recreate already deleted the pod; the engine
        # recreates it with the new generation label.
        return True

    def _mark_current(self, pod: Pod, gen: str, annotations=None) -> None:
        try:
            self.cluster.patch_meta(
                Pod, pod.metadata.namespace, pod.metadata.name,
                labels={constants.LABEL_JOB_GENERATION: gen},
                annotations=annotations)
        except NotFoundError:
            pass

    @staticmethod
    def _task_identity(pod: Pod):
        try:
            task_type = TaskType.normalize(
                pod.metadata.labels.get(constants.LABEL_TASK_TYPE, ""))
            index = int(pod.metadata.labels.get(constants.LABEL_TASK_INDEX, "-1"))
        except ValueError:
            return None, -1
        return task_type, index

    @staticmethod
    def _in_range(job: TPUJob, pod: Pod) -> bool:
        raw_type = pod.metadata.labels.get(constants.LABEL_TASK_TYPE, "")
        try:
            task_type = TaskType.normalize(raw_type)
        except ValueError:
            return False
        task = job.spec.tasks.get(task_type)
        if task is None:
            return False
        try:
            index = int(pod.metadata.labels.get(constants.LABEL_TASK_INDEX, "-1"))
        except ValueError:
            return False
        return 0 <= index < task.num_tasks
