"""The shared control-loop kernel: observe → decide → commit, once.

Every closed loop in the stack — the ElasticAutoscaler over TPUJobs, the
FleetAutoscaler over InferenceServices, the per-pool prefill/decode
recommenders — is the same machine: observe a signal window, decide
under cooldown/hysteresis/staleness/flap-damping discipline, commit the
change through an optimistic-concurrency write, and burn tempo state
ONLY after the write lands. Until now each loop hand-rolled that
machine; this module is the one copy (ROADMAP item 4's kernel half —
the precondition for the cluster-in-a-process twin being able to run
the real loops against simulated devices):

* **``LoopKernel``** — the template. Subclasses implement ``observe``
  (None = nothing to decide on yet: world assembling, not registered),
  ``decide`` (a decision object with ``action``/``current``/``target``/
  ``reason``/``seq`` — any path that declines must
  ``return self.skip(reason)``, never a bare None), and ``commit``
  (execute; return a `obs/ledger` commit-outcome string — ``landed``,
  ``conflict:<Type>``, ``fallback:<why>``). ``run_tick`` is the ONLY
  driver: it advances the open effect horizon, records the decision
  (subclass ``record`` hook — the loop's decision log, byte-compatible
  with the pre-kernel formats), commits actionable decisions, and
  appends exactly one ledger ``DecisionRecord`` carrying the whole
  tick. The ``ledger-coverage`` analyzer pass enforces the contract
  statically: no decide/commit path in a kernel subclass can skip the
  ledger, and nothing may call decide/commit around ``run_tick``.
* **``CooldownGate``** — the tempo state every loop shares: separate
  up/down cooldowns, flap damping on direction reversals, and the
  commit-only-after-patch rule (a failed patch burns no cooldown).
  Extracted from `autoscale/policy.Recommender`, which now rides it.
* **The one decision-line serializer** — ``format_decision_line`` /
  ``format_commit_failure_line`` / ``parse_decision_line``. The three
  formats that had drifted apart (the FleetAutoscaler's service lines,
  its pool lines, and its patch-failure lines — plus the
  ElasticAutoscaler's new log) are all renderings of one record shape;
  the parser accepts every historical variant, so old soak logs still
  parse (round-trip pinned by `tests/test_ledger.py`).

Stdlib-only (plus `obs/ledger`): the digital-twin roadmap item will
import this without dragging in jax or the client stack.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Tuple

from tpu_on_k8s.obs import ledger as ledger_mod
from tpu_on_k8s.obs.ledger import COMMIT_NONE, committed

#: the hold action shared by every loop's decision vocabulary
#: (mirrors `autoscale/policy.ACTION_HOLD` — one string, two importers)
ACTION_HOLD = "hold"
ACTION_SKIP = "skip"


# --------------------------------------------------------------- tempo state
class CooldownGate:
    """Cooldown + flap-damping stamps with commit-only-after-patch
    semantics — the tempo half of every decision loop, in one place.

    ``commit(action, now)`` is called ONLY after the executing write
    lands (the kernel's commit hook / `Recommender.commit`), so a
    failed patch burns no cooldown and the loop retries at full speed
    next tick instead of sulking through a window it never used."""

    def __init__(self, up_cooldown_s: float = 0.0,
                 down_cooldown_s: float = 0.0,
                 flap_guard_s: float = 0.0) -> None:
        self.up_cooldown_s = up_cooldown_s
        self.down_cooldown_s = down_cooldown_s
        self.flap_guard_s = flap_guard_s
        self.last_up_t: Optional[float] = None
        self.last_down_t: Optional[float] = None

    def up_in_cooldown(self, now: float) -> bool:
        return (self.last_up_t is not None
                and now - self.last_up_t < self.up_cooldown_s)

    def down_in_cooldown(self, now: float) -> bool:
        return (self.last_down_t is not None
                and now - self.last_down_t < self.down_cooldown_s)

    def flap_blocked(self, action: str, now: float) -> bool:
        """A direction reversal needs ``flap_guard_s`` since the
        opposite move executed."""
        if action == "up":
            return (self.last_down_t is not None
                    and now - self.last_down_t < self.flap_guard_s)
        if action == "down":
            return (self.last_up_t is not None
                    and now - self.last_up_t < self.flap_guard_s)
        return False

    def commit(self, action: str, now: float) -> None:
        if action == "up":
            self.last_up_t = now
        elif action == "down":
            self.last_down_t = now


# ------------------------------------------------------- decision-line serde
@dataclasses.dataclass(frozen=True)
class DecisionLine:
    """One parsed decision-log line. ``scope`` is the ordered prefix
    (``(("svc", key),)``, ``(("svc", key), ("pool", p))``,
    ``(("job", key),)``, or empty for a bare `policy.Decision.line()`);
    ``failure`` is the exception type name of a ``patch_failed`` line
    (empty for decision lines)."""

    seq: int
    action: str = ""
    current: int = 0
    target: int = 0
    reason: str = ""
    scope: Tuple[Tuple[str, str], ...] = ()
    failure: str = ""

    def line(self) -> str:
        if self.failure:
            return format_commit_failure_line(self.seq, self.failure,
                                              scope=self.scope)
        return format_decision_line(self.seq, self.action, self.current,
                                    self.target, self.reason,
                                    scope=self.scope)


def _scope_prefix(scope: Iterable[Tuple[str, str]]) -> str:
    return "".join(f"{k}={v} " for k, v in scope)


def format_decision_line(seq: int, action: str, current: int, target: int,
                         reason: str, *,
                         scope: Iterable[Tuple[str, str]] = ()) -> str:
    """The ONE decision-line renderer. Byte-compatible with every
    pre-kernel format: the FleetAutoscaler's
    ``svc=<key> seq=N action=a replicas=c->t reason=...``, its pool
    variant (``pool=<p>`` after ``svc=``), and the bare
    `autoscale/policy.Decision.line()` form (empty scope)."""
    return (f"{_scope_prefix(scope)}seq={seq} action={action} "
            f"replicas={current}->{target} reason={reason}")


def format_commit_failure_line(seq: int, failure: str, *,
                               scope: Iterable[Tuple[str, str]] = ()) -> str:
    """The commit-failure line (``patch_failed <ExcType>``) — appended
    after the decision line when the executing write did not land."""
    return f"{_scope_prefix(scope)}seq={seq} patch_failed {failure}"


#: scope keys a decision line may carry, in their canonical order
_SCOPE_KEYS = ("svc", "job", "pool", "lane")


def parse_decision_line(line: str) -> Optional[DecisionLine]:
    """Parse any decision-log line (all historical formats) back into a
    ``DecisionLine``; None if the line is not one. ``reason`` is
    everything after ``reason=`` verbatim (reasons contain spaces), so
    ``parse → format`` round-trips byte-identically."""
    rest = line.strip()
    scope: List[Tuple[str, str]] = []
    seq = None
    while rest:
        head, _, tail = rest.partition(" ")
        key, eq, value = head.partition("=")
        if not eq or not value:
            return None
        if key == "seq":
            try:
                seq = int(value)
            except ValueError:
                return None
            rest = tail
            break
        if key not in _SCOPE_KEYS:
            return None
        scope.append((key, value))
        rest = tail
    if seq is None:
        return None
    tail = rest
    if tail.startswith("patch_failed "):
        failure = tail[len("patch_failed "):]
        if not failure:
            return None
        return DecisionLine(seq=seq, scope=tuple(scope), failure=failure)
    if not tail.startswith("action="):
        return None
    body, sep, reason = tail.partition(" reason=")
    if not sep:
        return None
    fields = dict(part.partition("=")[::2] for part in body.split(" "))
    replicas = fields.get("replicas", "")
    cur_s, sep2, tgt_s = replicas.partition("->")
    if not sep2:
        return None
    try:
        current, target = int(cur_s), int(tgt_s)
    except ValueError:
        return None
    return DecisionLine(seq=seq, action=fields.get("action", ""),
                        current=current, target=target, reason=reason,
                        scope=tuple(scope))


# ------------------------------------------------------------------ horizons
@dataclasses.dataclass
class OpenHorizon:
    """The effect horizon of the loop's last committed decision: the
    ledger seq to close against, what was committed, and which
    intermediate events have already been noted (so ``replicas_ready``
    lands once, not once per tick)."""

    seq: int
    action: str
    target: int
    trigger: str = ""
    noted: set = dataclasses.field(default_factory=set)


# -------------------------------------------------------------------- kernel
class LoopKernel:
    """The observe→decide→commit template (see module doc).

    Subclass hook contract (enforced by the ``ledger-coverage``
    analyzer pass):

    * ``observe(ctx)`` → pack or None (None = no decision exists this
      tick — world assembling, loop frozen; nothing is ledgered).
    * ``decide(pack, ctx)`` → decision or ``self.skip(reason)``. A
      decision duck-types ``seq``/``action``/``current``/``target``/
      ``reason`` (`autoscale/policy.Decision` is the canonical shape).
      Bare ``return None`` is a finding: a declined decision must go
      through ``skip`` so the ledger still sees the tick.
    * ``commit(pack, decision, ctx)`` → a commit-outcome string
      (`obs/ledger.COMMIT_*` vocabulary). Every return must carry the
      outcome; raising is fine (the kernel ledgers ``conflict:<Type>``
      and re-raises).
    * ``record(pack, decision, ctx)`` — the loop's own decision log +
      gauges (byte-compatible with its pre-kernel format).
    * ``signals_of`` / ``exemplars_of`` / ``trigger_of`` /
      ``horizon_events`` — the provenance detail hooks.

    ``run_tick`` is the only entry point; overriding it (or calling
    ``decide``/``commit`` directly) bypasses the ledger and is itself
    a finding."""

    def __init__(self, loop_id: str = "", *, ledger=None) -> None:
        self.loop_id = loop_id
        self.ledger = ledger_mod.ensure(ledger)
        #: loop-local tick counter (one counter across live AND dead
        #: observations — subclasses advance it in ``observe``)
        self.seq = 0
        #: ledger seq of the loop's last landed decision (parent link)
        self.last_committed: Optional[int] = None
        self.open_horizon: Optional[OpenHorizon] = None

    def bind(self, loop_id: str, ledger) -> None:
        """Late-bind identity + ledger (loop states are often minted
        bare by a registry before the owning controller is known)."""
        self.loop_id = loop_id
        self.ledger = ledger_mod.ensure(ledger)

    # ------------------------------------------------------------- template
    def run_tick(self, ctx: Optional[Dict[str, Any]] = None):
        """One loop iteration. Returns the decision (None when observe
        or decide declined)."""
        ctx = {} if ctx is None else ctx
        pack = self.observe(ctx)
        if pack is None:
            return None
        self._advance_horizon(pack, ctx)
        decision = self.decide(pack, ctx)
        if decision is None:
            return None               # decide() ledgered the skip itself
        ctx["decision"] = decision    # provenance hooks may inspect it
        self.record(pack, decision, ctx)
        outcome = COMMIT_NONE
        if self.actionable(decision, ctx):
            try:
                outcome = self.commit(pack, decision, ctx)
            except Exception as e:
                # the write path blew up: ledger the conflict before the
                # caller's error handling sees it — a crashed commit must
                # not be a decision that never happened
                self._ledger_tick(pack, decision,
                                  f"conflict:{type(e).__name__}", ctx)
                raise
        self._ledger_tick(pack, decision, outcome, ctx)
        return decision

    def abandon(self, event: str = ledger_mod.HORIZON_ABANDONED) -> None:
        """Close the loop's open effect horizon because the LOOP is
        being retired (its object deleted, the service deregistered) —
        without this, an unclosable horizon pins the shared ledger's
        ``open_effect_horizons`` gauge for the rest of the process."""
        h = self.open_horizon
        if h is not None:
            self.open_horizon = None
            self.ledger.horizon(h.seq, loop=self.loop_id, event=event,
                                closing=True)

    def skip(self, reason: str, *, tick: Optional[int] = None) -> None:
        """The one legal way for ``decide`` to decline: the tick still
        lands in the ledger (action ``skip``), so "the loop looked and
        chose not to decide" is distinguishable from "the loop never
        ran"."""
        self.ledger.decision(
            loop=self.loop_id, tick=self.seq if tick is None else tick,
            action=ACTION_SKIP, current=0, target=0, reason=reason,
            commit=COMMIT_NONE, parent=self.last_committed)
        return None

    # ------------------------------------------------------- subclass hooks
    def observe(self, ctx: Dict[str, Any]):
        raise NotImplementedError

    def decide(self, pack, ctx: Dict[str, Any]):
        raise NotImplementedError

    def commit(self, pack, decision, ctx: Dict[str, Any]) -> str:
        raise NotImplementedError

    def record(self, pack, decision, ctx: Dict[str, Any]) -> None:
        """The loop's own decision log / gauges; default: nothing."""

    def actionable(self, decision, ctx: Dict[str, Any]) -> bool:
        return (decision.action not in (ACTION_HOLD, ACTION_SKIP)
                and decision.target != decision.current)

    def opens_horizon(self, decision, outcome: str,
                      ctx: Dict[str, Any]) -> bool:
        """Whether a landed commit opens an effect horizon. Default:
        every landed commit does. A loop that KNOWS it will never
        observe the effect (e.g. a rescale that also freezes the loop —
        no future tick exists to close the horizon) must return False:
        an unclosable horizon pins the open_effect_horizons gauge and
        turns normal convergence into a standing alert."""
        return committed(outcome)

    def tick_of(self, pack) -> int:
        return self.seq

    def signals_of(self, pack) -> Tuple[Tuple[str, str], ...]:
        return ()

    def exemplars_of(self, pack) -> Tuple[int, ...]:
        return ()

    def trigger_of(self, pack, ctx: Dict[str, Any]) -> str:
        return ""

    def horizon_events(self, horizon: OpenHorizon, pack,
                       ctx: Dict[str, Any]) -> Iterable[Tuple[str, bool]]:
        """New effect-horizon events observed this tick, as
        ``(event, closing)`` pairs. The kernel de-duplicates against
        ``horizon.noted`` and stops at the first closing event."""
        return ()

    def on_committed(self, rec, decision, outcome: str,
                     ctx: Dict[str, Any]) -> None:
        """Called after a landed commit's bookkeeping (``rec`` is the
        real ledger record). Loops that track cross-decision episodes
        (e.g. which decision answered an SLO page) hook here."""

    # ------------------------------------------------------------- plumbing
    def _advance_horizon(self, pack, ctx: Dict[str, Any]) -> None:
        h = self.open_horizon
        if h is None:
            return
        for event, closing in self.horizon_events(h, pack, ctx):
            if event in h.noted:
                continue
            h.noted.add(event)
            self.ledger.horizon(h.seq, loop=self.loop_id, event=event,
                                closing=closing)
            if closing:
                self.open_horizon = None
                return

    def _ledger_tick(self, pack, decision, outcome: str,
                     ctx: Dict[str, Any]) -> None:
        trigger = self.trigger_of(pack, ctx)
        landed = committed(outcome)
        opens = landed and self.opens_horizon(decision, outcome, ctx)
        rec = self.ledger.decision(
            loop=self.loop_id, tick=self.tick_of(pack),
            action=decision.action, current=decision.current,
            target=decision.target, reason=decision.reason,
            commit=outcome, trigger=trigger, parent=self.last_committed,
            signals=self.signals_of(pack),
            exemplars=self.exemplars_of(pack),
            horizon_open=opens)
        if rec is None or not landed:
            return
        if self.open_horizon is not None:
            # a newer commit took over before the previous effect was
            # observed: close the stale horizon explicitly — an operator
            # reading the chain must see the takeover, and the
            # open_effect_horizons gauge must not leak
            self.ledger.horizon(self.open_horizon.seq, loop=self.loop_id,
                                event=ledger_mod.HORIZON_SUPERSEDED,
                                closing=True)
            self.open_horizon = None
        self.last_committed = rec.seq
        if opens:
            self.open_horizon = OpenHorizon(rec.seq, decision.action,
                                            decision.target,
                                            trigger=trigger)
        self.on_committed(rec, decision, outcome, ctx)
