"""Dump-file I/O shared by every canonical-artifact reader/writer:
transparent ``.gz`` support with DETERMINISTIC compression.

A million-request twin run dumps spans/ledger/budget files that are
pointlessly large as plain JSON (the span dump compresses ~20x), so the
trace/ledger/SLO writers and all three report loaders route through
``open_dump``: any path ending in ``.gz`` is gzipped transparently,
everything else is untouched plain text.

The subtlety this module exists for: ``gzip.open`` embeds the CURRENT
WALL TIME in the member header (RFC 1952 MTIME), which would break the
byte-identical-across-runs property every soak and `make twin-soak`
assert. Writes therefore pin ``mtime=0`` and embed no filename — the
compressed bytes are a pure function of the payload.
"""
from __future__ import annotations

import gzip
import io


class _GzTextWriter(io.TextIOWrapper):
    """Text writer onto a deterministic gzip member: ``mtime=0``, no
    embedded filename. Closes the underlying file too (``GzipFile``
    deliberately leaves a caller-supplied fileobj open)."""

    def __init__(self, path: str) -> None:
        self._raw = open(path, "wb")
        try:
            self._gz = gzip.GzipFile(filename="", mode="wb",
                                     fileobj=self._raw, mtime=0)
        except BaseException:
            self._raw.close()
            raise
        super().__init__(self._gz, encoding="utf-8", newline="")

    def close(self) -> None:
        try:
            super().close()
        finally:
            self._raw.close()


def is_gz(path: str) -> bool:
    return str(path).endswith(".gz")


def open_dump(path: str, mode: str = "r"):
    """Open a dump file for text ``"r"`` or ``"w"``, honoring ``.gz``.
    Returns a context-manager file object either way, so call sites are
    one-line swaps for ``open(path, mode)``."""
    p = str(path)
    if mode not in ("r", "w"):
        raise ValueError(f"open_dump supports text 'r'/'w', got {mode!r}")
    if not is_gz(p):
        return open(p, mode)
    if mode == "w":
        return _GzTextWriter(p)
    return gzip.open(p, "rt", encoding="utf-8")
