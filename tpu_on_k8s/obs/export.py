"""Trace export: Chrome trace-event / Perfetto JSON + the crash flight
recorder.

Two consumers of the span substrate (`obs/trace.py`):

* **Offline analysis** — ``to_chrome_trace`` renders finished spans as
  the Chrome trace-event format (``chrome://tracing`` / Perfetto /
  ``ui.perfetto.dev`` all load it): one complete ``"X"`` event per span
  (tracks keyed by trace id so one request's whole life reads as one
  row), one instant ``"i"`` event per span event (first token, chaos
  injection, replay). Deterministic: events sort by (ts, span id), no
  wall-clock metadata.

* **Crash forensics** — ``FlightRecorder``: a bounded ring of the most
  recently finished spans, dumped to a file when something dies
  (``EngineCrashError`` recovery, ``RETRY_EXHAUSTED`` finalization —
  the gateway/disagg fleet call ``tracer.crash_dump(reason)``). The
  ring costs O(capacity) host RAM forever; the dump is the last N spans
  of context an operator needs to see *what the engine was doing when
  it died* without having traced the whole run. Dump filenames are
  sequence-numbered, never timestamped — a seeded chaos run produces
  the same filenames every time.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

from tpu_on_k8s.obs.trace import Span, TRACE_FORMAT


def _as_dict(span) -> Dict[str, Any]:
    return span.to_dict() if isinstance(span, Span) else dict(span)


def to_chrome_trace(spans: Iterable, *, service: str = "tpu-on-k8s"
                    ) -> Dict[str, Any]:
    """Render spans (``Span`` objects or their dicts) as a Chrome
    trace-event document. Timestamps convert to microseconds (the
    format's unit); the ``tid`` is the trace id, so every span of one
    request stacks on one named track."""
    events: List[Dict[str, Any]] = []
    for s in map(_as_dict, spans):
        if not s or s.get("end") is None:
            continue
        ts = s["start"] * 1e6
        args = dict(s.get("attrs", {}))
        args["span"] = s["span"]
        if s.get("parent") is not None:
            args["parent"] = s["parent"]
        if s.get("status") not in (None, "ok"):
            args["status"] = s["status"]
        events.append({
            "ph": "X", "name": s["name"], "cat": "span",
            "pid": 1, "tid": s["trace"],
            "ts": round(ts, 3), "dur": round((s["end"] - s["start"]) * 1e6, 3),
            "args": args,
        })
        for ev in s.get("events", ()):
            events.append({
                "ph": "i", "name": ev["name"], "cat": "event",
                "pid": 1, "tid": s["trace"], "s": "t",
                "ts": round(ev["t"] * 1e6, 3),
                "args": dict(ev.get("attrs", {}), span=s["span"]),
            })
    events.sort(key=lambda e: (e["ts"], e["args"].get("span", 0),
                               0 if e["ph"] == "X" else 1))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"service": service, "format": TRACE_FORMAT}}


def dump_chrome_trace(spans: Iterable, path: str, *,
                      service: str = "tpu-on-k8s") -> None:
    doc = to_chrome_trace(spans, service=service)
    with open(path, "w") as f:
        json.dump(doc, f, sort_keys=True, separators=(",", ":"))
        f.write("\n")


def load_trace(path: str) -> List[Dict[str, Any]]:
    """Read a ``Tracer.dump`` file back into span dicts (what
    `tools/trace_report.py` consumes), ``.json`` or ``.json.gz``;
    raises ``ValueError`` on a file that is not a trace dump."""
    from tpu_on_k8s.obs.dumpio import open_dump
    with open_dump(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("format") != TRACE_FORMAT:
        raise ValueError(f"{path} is not a {TRACE_FORMAT} dump")
    return doc["spans"]


class FlightRecorder:
    """Bounded ring of recently finished spans + the crash-dump writer.

    ``capacity`` bounds host RAM (spans are stored as their export
    dicts — no live references pinning engines or request records).
    ``directory`` is where dumps land; with ``None`` the recorder still
    rings (tests read ``snapshot()``) but ``dump`` returns None."""

    def __init__(self, capacity: int = 512,
                 directory: Optional[str] = None,
                 prefix: str = "flightrec") -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.directory = directory
        self.prefix = prefix
        self.dumps: List[str] = []
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, span) -> None:
        with self._lock:
            self._ring.append(_as_dict(span))

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str) -> Optional[str]:
        """Persist the ring as one JSON artifact. The filename carries a
        sequence number and the (sanitized) reason — stable across
        seeded replays, unique within a process (this counter is the
        ONE allocator; `Tracer.crash_dump` delegates here, so mixed
        direct/tracer dumps can never collide on a path)."""
        with self._lock:
            spans = list(self._ring)
            self._seq += 1
            seq = self._seq
        if self.directory is None:
            return None
        safe = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason) or "unknown"
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory,
                            f"{self.prefix}-{seq:04d}-{safe}.json")
        doc = {"format": TRACE_FORMAT, "reason": reason, "seq": seq,
               "spans": spans}
        with open(path, "w") as f:
            json.dump(doc, f, sort_keys=True, separators=(",", ":"))
            f.write("\n")
        self.dumps.append(path)
        return path
