"""Decision ledger: one byte-replayable provenance record for every
control-loop decision.

Five control loops now close independently (ElasticAutoscaler,
FleetAutoscaler service + per-pool recommenders, the rollout machinery
they drive), chaos injects faults, and the SLO engine pages — each with
its own log. Nothing joins "SLO paged" → "autoscaler decided 4→6" →
"patch landed" → "burn recovered" into one answerable chain. This module
is that join point:

* **``DecisionRecord``** — one loop decision, typed: the loop id, the
  loop-local observation tick, the observed signals (pre-formatted
  strings, so the serialized form is stable by construction), trace-id
  exemplars tying the signals back to the request spans that produced
  them, the triggering SLO page episode or chaos event, a parent link to
  the loop's previous committed decision, the decide outcome
  (action/current→target/reason), and the commit outcome — ``landed``,
  ``conflict:<Type>`` (the patch never happened, no cooldown burned), or
  ``fallback:<Type>`` (the patch landed but in-process execution
  deferred to the reconciler).
* **``HorizonRecord``** — the *effect horizon* of a committed decision:
  opened at commit, progressed/closed later when the effect is observed
  — the replicas go ready, the rollout/drain completes, or the SLO burn
  recovers. The chain `tools/why_report.py` renders ends here.
* **``DecisionLedger``** — an injectable-clock, append-only record list
  with ONE monotone sequence counter. Ids come from the counter and
  timestamps from the injected clock, so two runs of the same seeded
  trace produce **byte-identical dumps** (``make why-demo`` asserts
  exactly this — the same contract as `obs/trace.Tracer`).
* **``NOOP``** — the disabled ledger: every record method no-ops and
  returns None, reads no clock, takes no lock, allocates nothing per
  call — a loop running without a ledger is bit-for-bit on its
  pre-ledger behavior, so every existing determinism proof survives.

The loops themselves never import this module's internals directly:
they ride `controller/loopkernel.LoopKernel`, whose observe→decide→
commit template emits exactly one ``DecisionRecord`` per decision (the
``ledger-coverage`` analyzer pass enforces that no decide/commit path
can skip it).

Stdlib-only, importable from any layer — the same discipline as
`obs/trace.py` and `chaos/faults.py`.
"""
from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

#: the ledger-file format tag `tools/why_report.py` checks
LEDGER_FORMAT = "tpu-on-k8s-ledger/v1"

# ------------------------------------------------------------ commit outcomes
#: decide held / skipped: nothing was executed, no effect horizon exists
COMMIT_NONE = "none"
#: the patch landed (and any in-process apply succeeded)
COMMIT_LANDED = "landed"
#: prefix of "the write did not land" outcomes (``conflict:<ExcType>``):
#: the scale never happened and no cooldown was burned
COMMIT_CONFLICT = "conflict"
#: prefix of "the patch landed but in-process execution deferred"
#: outcomes (``fallback:<why>``): the reconciler converges later
COMMIT_FALLBACK = "fallback"

#: horizon-close outcomes (`ISSUE`: the three observable effect ends)
HORIZON_REPLICAS_READY = "replicas_ready"
HORIZON_ROLLOUT_COMPLETE = "rollout_complete"
HORIZON_BURN_RECOVERED = "burn_recovered"
#: a newer committed decision took over before this one's effect landed
HORIZON_SUPERSEDED = "superseded"
#: the loop itself was retired (object deleted, service deregistered)
#: before the effect was observed — closed so the gauge cannot pin
HORIZON_ABANDONED = "abandoned"


def committed(outcome: str) -> bool:
    """True when a commit outcome means the write LANDED (``landed`` or
    ``fallback:*`` — a deferred in-process apply still changed the
    spec; only ``none``/``conflict:*`` mean nothing happened)."""
    return outcome == COMMIT_LANDED or outcome.startswith(COMMIT_FALLBACK)


@dataclasses.dataclass(frozen=True)
class DecisionRecord:
    """One control-loop decision (see module doc). ``signals`` are
    pre-formatted ``(key, value)`` string pairs — formatting at record
    time is what makes the serialized ledger stable by construction;
    ``exemplars`` are trace ids (`obs/trace.py` counter ids) of the
    requests whose latency observations backed the signals."""

    seq: int
    t: float
    loop: str
    tick: int
    action: str
    current: int
    target: int
    reason: str
    commit: str = COMMIT_NONE
    trigger: str = ""                 # "slo_page:<svc>#N" | "chaos#N" | ""
    parent: Optional[int] = None      # seq of the loop's previous commit
    signals: Tuple[Tuple[str, str], ...] = ()
    exemplars: Tuple[int, ...] = ()
    horizon: str = "none"             # "open" | "none"

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "kind": "decision", "seq": self.seq, "t": self.t,
            "loop": self.loop, "tick": self.tick, "action": self.action,
            "current": self.current, "target": self.target,
            "reason": self.reason, "commit": self.commit,
            "horizon": self.horizon,
        }
        if self.trigger:
            d["trigger"] = self.trigger
        if self.parent is not None:
            d["parent"] = self.parent
        if self.signals:
            d["signals"] = {k: v for k, v in self.signals}
        if self.exemplars:
            d["exemplars"] = list(self.exemplars)
        return d

    def line(self) -> str:
        """One stable human-grep-able line (debugging; the canonical
        byte-compared artifact is the JSON dump)."""
        parts = [f"seq={self.seq}", f"t={self.t:.6f}", f"loop={self.loop}",
                 f"tick={self.tick}", f"action={self.action}",
                 f"replicas={self.current}->{self.target}",
                 f"commit={self.commit}"]
        if self.trigger:
            parts.append(f"trigger={self.trigger}")
        if self.parent is not None:
            parts.append(f"parent={self.parent}")
        parts.append(f"reason={self.reason}")
        return " ".join(parts)


@dataclasses.dataclass(frozen=True)
class HorizonRecord:
    """One effect-horizon event for a committed decision: ``closing``
    ends the horizon (``event`` says why); a non-closing event marks
    intermediate progress (e.g. ``replicas_ready`` on an SLO-paged
    scale-up that still waits for the burn to recover)."""

    seq: int
    t: float
    loop: str
    decision: int                      # seq of the DecisionRecord
    event: str
    closing: bool

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": "horizon", "seq": self.seq, "t": self.t,
                "loop": self.loop, "decision": self.decision,
                "event": self.event, "closing": self.closing}

    def line(self) -> str:
        return (f"seq={self.seq} t={self.t:.6f} loop={self.loop} "
                f"horizon decision={self.decision} event={self.event} "
                f"closing={int(self.closing)}")


Record = Union[DecisionRecord, HorizonRecord]


class _NoopLedger:
    """Ledger disabled: no clock reads, no locks, no allocation per call
    — bit-for-bit behavior-neutral, the same contract as the NOOP
    tracer (every determinism proof that predates the ledger survives
    running "with" it)."""

    __slots__ = ()
    enabled = False
    records: Tuple = ()

    def decision(self, **kw) -> None:
        return None

    def horizon(self, decision: int, *, loop: str, event: str,
                closing: bool) -> None:
        return None

    def open_horizons(self) -> int:
        return 0

    def lines(self) -> List[str]:
        return []

    def export(self) -> List[Dict[str, Any]]:
        return []

    def dump(self, path: str, extra: Optional[Dict[str, Any]] = None) -> None:
        raise RuntimeError("decision ledger is disabled (NOOP has no records)")


NOOP = _NoopLedger()


def ensure(ledger) -> Any:
    """The one idiom every kernel-carrying constructor uses:
    ``self.ledger = ensure(ledger)`` — None means disabled."""
    return NOOP if ledger is None else ledger


class DecisionLedger:
    """Append-only decision provenance (see module doc). ``clock`` is
    injectable — pass the driver's virtual clock and the whole ledger
    becomes a pure function of the seed. ``max_records`` bounds host
    RAM on a long-lived operator: past the cap, appends are counted in
    ``dropped`` instead of retained (the same retention posture as
    `obs/trace.Tracer.max_spans`).

    ``metrics`` is an optional `metrics.LedgerMetrics`: every decision
    increments ``decisions`` (labelled ``<loop>|<outcome-class>``),
    conflicts increment ``commit_failures``, and the
    ``open_effect_horizons`` gauge tracks decisions whose effect has
    not yet been observed — a climbing gauge means the loops are
    committing changes whose effects never land."""

    enabled = True

    def __init__(self, clock: Callable[[], float] = time.monotonic, *,
                 metrics=None, max_records: int = 200_000) -> None:
        if max_records < 1:
            raise ValueError(f"max_records must be >= 1, got {max_records}")
        self.clock = clock
        self.metrics = metrics
        self.max_records = max_records
        self.records: List[Record] = []
        self.dropped = 0
        self._lock = threading.Lock()
        self._next_seq = 1
        self._open: Dict[int, str] = {}    # decision seq -> loop

    # ------------------------------------------------------------- recording
    def decision(self, *, loop: str, tick: int, action: str, current: int,
                 target: int, reason: str, commit: str = COMMIT_NONE,
                 trigger: str = "", parent: Optional[int] = None,
                 signals: Tuple[Tuple[str, str], ...] = (),
                 exemplars: Tuple[int, ...] = (),
                 horizon_open: bool = False) -> Optional[DecisionRecord]:
        """Record one decision; returns the record (None only from the
        NOOP ledger). ``horizon_open`` marks the decision as having an
        effect still to be observed — close it with ``horizon``."""
        t = self.clock()
        with self._lock:
            rec = DecisionRecord(
                seq=self._next_seq, t=t, loop=loop, tick=tick,
                action=action, current=current, target=target,
                reason=reason, commit=commit, trigger=trigger,
                parent=parent, signals=tuple(signals),
                exemplars=tuple(exemplars),
                horizon="open" if horizon_open else "none")
            self._next_seq += 1
            self._append_locked(rec)
            if horizon_open:
                self._open[rec.seq] = loop
            n_open = len(self._open)
        if self.metrics is not None:
            outcome = commit.split(":", 1)[0]
            if action == "skip":
                outcome = "skip"
            elif commit == COMMIT_NONE:
                outcome = "hold"
            self.metrics.inc("decisions", label=f"{loop}|{outcome}")
            if commit.startswith(COMMIT_CONFLICT):
                self.metrics.inc("commit_failures")
            self.metrics.set_gauge("open_effect_horizons", n_open)
        return rec

    def horizon(self, decision: int, *, loop: str, event: str,
                closing: bool) -> Optional[HorizonRecord]:
        """Record effect-horizon progress for a committed decision."""
        t = self.clock()
        with self._lock:
            rec = HorizonRecord(seq=self._next_seq, t=t, loop=loop,
                                decision=decision, event=event,
                                closing=closing)
            self._next_seq += 1
            self._append_locked(rec)
            if closing:
                self._open.pop(decision, None)
            n_open = len(self._open)
        if self.metrics is not None:
            self.metrics.set_gauge("open_effect_horizons", n_open)
        return rec

    def _append_locked(self, rec: Record) -> None:
        if len(self.records) < self.max_records:
            self.records.append(rec)
        else:
            self.dropped += 1

    # -------------------------------------------------------------- reading
    def open_horizons(self) -> int:
        with self._lock:
            return len(self._open)

    def export(self) -> List[Dict[str, Any]]:
        """Records as dicts, in append order (seq order — one counter)."""
        with self._lock:
            records = list(self.records)
        return [r.to_dict() for r in records]

    def lines(self) -> List[str]:
        with self._lock:
            records = list(self.records)
        return [r.line() for r in records]

    def dump(self, path: str,
             extra: Optional[Dict[str, Any]] = None) -> None:
        """Write the canonical ledger file. ``sort_keys`` + fixed
        separators + injected-clock timestamps only: two seeded runs
        produce byte-identical files (`make why-demo` byte-compares
        them). ``extra`` carries the sibling logs `tools/why_report.py`
        joins against (per-service SLO event logs, the chaos injector's
        sequence-stamped event log). File I/O happens outside the
        ledger lock. A ``.gz`` path gzips deterministically
        (`obs/dumpio.py`)."""
        from tpu_on_k8s.obs.dumpio import open_dump
        doc: Dict[str, Any] = {"format": LEDGER_FORMAT,
                               "dropped": self.dropped,
                               "records": self.export()}
        if extra:
            doc.update(extra)
        with open_dump(path, "w") as f:
            json.dump(doc, f, sort_keys=True, separators=(",", ":"))
            f.write("\n")


def load_ledger(path: str) -> Dict[str, Any]:
    """Read a ``DecisionLedger.dump`` file back (the whole doc — records
    plus any embedded sibling logs, ``.json`` or ``.json.gz``); raises
    ``ValueError`` on a file that is not a ledger dump."""
    from tpu_on_k8s.obs.dumpio import open_dump
    with open_dump(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("format") != LEDGER_FORMAT:
        raise ValueError(f"{path} is not a {LEDGER_FORMAT} dump")
    return doc
