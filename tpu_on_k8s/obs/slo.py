"""Deterministic SLO engine: declarative objectives, sliding windows,
multi-window error-budget burn rates, typed budget-state transitions.

The stack emits spans, histograms, and exemplars end-to-end (`obs/trace`,
`metrics/metrics`) and lints them (`tools/analyze`), but nothing
*interprets* them: autoscalers react to raw p95 thresholds, and "are we
inside our error budget" is not a question any existing signal answers.
This module is that interpretation layer — the shared SLO vocabulary the
capacity broker and digital twin (ROADMAP items 3–4) will read:

* **``SLOSpec``** — one declarative objective: *what* is measured
  (``ttft_p95`` / ``tpot_p95`` / ``queue_wait_p95`` / ``availability``),
  the target, and the compliance window. A pNN latency objective grants
  an error budget of ``(100-NN)%`` breaching requests over the window; an
  availability objective grants ``1 - target`` failed requests.
* **``SLOEvaluator``** — feeds good/bad events into a pruned sliding
  window and computes **multi-window burn rates**, SRE-style: the *fast*
  pair (5m/1h at a 30-day window; both must burn ≥ ``page_burn``) catches
  a sharp regression in minutes, the *slow* pair (6h/3d at ``warn_burn``)
  catches a slow bleed days before the budget empties. A pair's burn is
  the **min** of its two windows' burns (the long window is the
  confirmation, the short window the fast-reset) — exactly the
  multiwindow, multi-burn-rate alert the SRE workbook recommends.
* **``BudgetState``** — ``ok → warn → page → exhausted``, with a
  hysteresis dead band so a burn oscillating at the page threshold does
  not flap the state. Every transition lands in one deterministic
  ``event_log`` line, a ``budget_transitions`` counter, and (when a span
  is passed to ``evaluate``) a ``slo.transition`` span event.
* **``SLOEngine``** — a named set of evaluators sharing one clock and
  one event log: what `controller/fleetautoscaler.py` runs per service
  and `tools/serve_load.py --slo` runs per trace.

Staleness is explicit, never silent: past ``stale_after_s`` without a
single observation, burn rates report ``None`` (the windows have aged
dry) and the status carries ``stale=True`` — a dead signal source must
surface as *stale*, not as a frozen last-known burn rate (the same
no-data-is-not-zero discipline as `autoscale/signals.py`).

Deterministic by construction: every timestamp comes from the injected
clock, windows prune by arithmetic on those timestamps, and iteration
orders are insertion/sorted — two runs of the same seeded trace produce
byte-identical event logs (``make slo-soak`` asserts exactly this).
Stdlib-only, importable from any layer, like the rest of `obs/`.
"""
from __future__ import annotations

import dataclasses
import math
import re
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

# ------------------------------------------------------------ budget states
BUDGET_OK = "ok"
BUDGET_WARN = "warn"
BUDGET_PAGE = "page"
BUDGET_EXHAUSTED = "exhausted"

#: stable numeric encoding for the ``budget_state`` gauge (lands in
#: dashboards — append-only)
BUDGET_STATE_CODES = {BUDGET_OK: 0, BUDGET_WARN: 1, BUDGET_PAGE: 2,
                      BUDGET_EXHAUSTED: 3}

#: latency signal kinds a pNN objective can target — the names match the
#: `autoscale/signals.FleetSample` fields and the serving histograms
LATENCY_KINDS = ("ttft", "tpot", "queue_wait")

_PCTL_RE = re.compile(r"^(?P<kind>[a-z_]+)_p(?P<pct>\d{2})$")


def objective_kind(objective: str) -> Tuple[str, float]:
    """``(signal kind, error-budget fraction)`` of an objective name.

    ``ttft_p95`` → (``"ttft"``, 0.05): a p95 target means 5% of requests
    may breach it before the budget is spent. ``availability`` keys its
    budget off the spec target instead (fraction returned is 0.0 here and
    resolved by the evaluator as ``1 - target``). Raises ``ValueError``
    on anything else — an unknown objective must fail loudly at spec
    time, not silently never-page in production.
    """
    if objective == "availability":
        return "availability", 0.0
    m = _PCTL_RE.match(objective)
    if m is not None and m.group("kind") in LATENCY_KINDS:
        return m.group("kind"), (100 - int(m.group("pct"))) / 100.0
    raise ValueError(
        f"unknown SLO objective {objective!r} — expected 'availability' "
        f"or one of {LATENCY_KINDS} with a _pNN suffix (e.g. 'ttft_p95')")


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective. ``window_s`` is the compliance window
    the error budget covers; the four burn windows default to the SRE
    ratios of it (at the 30-day default: 5m/1h fast pair, 6h/3d slow
    pair) and may be set explicitly for virtual-clock traces. ``target``
    is seconds for latency objectives, a fraction (e.g. 0.999) for
    availability."""

    name: str
    objective: str = "ttft_p95"
    target: float = 0.0
    window_s: float = 2_592_000.0          # 30 days
    fast_short_s: float = 0.0              # 0 → window_s / 8640   (5m)
    fast_long_s: float = 0.0               # 0 → window_s / 720    (1h)
    slow_short_s: float = 0.0              # 0 → window_s / 120    (6h)
    slow_long_s: float = 0.0               # 0 → window_s / 10     (3d)
    page_burn: float = 14.4                # SRE fast-pair threshold
    warn_burn: float = 1.0                 # slow bleed: budget-rate 1x
    hysteresis: float = 0.2                # dead band leaving warn/page
    stale_after_s: float = 0.0             # 0 → fast_long_s

    def normalized(self) -> "SLOSpec":
        """Validated, defaults-resolved copy (the engine only ever holds
        normalized specs). Raises on an unknown objective or a
        non-positive target/window — a spec that can never evaluate is a
        configuration bug, not a runtime condition."""
        objective_kind(self.objective)     # raises on junk
        w = float(self.window_s)
        if w <= 0:
            raise ValueError(f"window_s must be > 0, got {w}")
        if float(self.target) <= 0:
            raise ValueError(f"target must be > 0, got {self.target}")
        fast_long = float(self.fast_long_s) or w / 720.0
        return SLOSpec(
            name=str(self.name),
            objective=str(self.objective),
            target=float(self.target),
            window_s=w,
            fast_short_s=float(self.fast_short_s) or w / 8640.0,
            fast_long_s=fast_long,
            slow_short_s=float(self.slow_short_s) or w / 120.0,
            slow_long_s=float(self.slow_long_s) or w / 10.0,
            page_burn=max(float(self.page_burn), 1.0),
            warn_burn=max(float(self.warn_burn), 0.0),
            hysteresis=min(max(float(self.hysteresis), 0.0), 0.9),
            stale_after_s=float(self.stale_after_s) or fast_long)

    @property
    def budget_fraction(self) -> float:
        """The allowed bad-event fraction over the compliance window."""
        kind, frac = objective_kind(self.objective)
        if kind == "availability":
            return max(1.0 - float(self.target), 1e-9)
        return frac


@dataclasses.dataclass(frozen=True)
class SLOStatus:
    """One evaluation's output: the typed budget state plus the numbers
    behind it. ``burn_fast`` / ``burn_slow`` are the pair burns (min of
    each pair's two windows) — ``None`` when a window holds no events
    (no data is never a burn rate of zero). ``budget_remaining`` is the
    fraction of the window's error budget left (negative = overdrawn)."""

    name: str
    objective: str
    target: float
    state: str
    burn_fast: Optional[float]
    burn_slow: Optional[float]
    budget_remaining: float
    good: int                              # events in the full window
    bad: int
    stale: bool

    @property
    def code(self) -> int:
        return BUDGET_STATE_CODES.get(self.state, -1)


def _fmt(v: Optional[float]) -> str:
    return "none" if v is None else f"{v:.6f}"


class _EventWindow:
    """Sliding good/bad event counts, pruned to the longest horizon.
    Boundary rule: an event at exactly ``now - horizon`` is OUTSIDE the
    window (windows are half-open ``(now - h, now]``) — pinned by the
    window-boundary determinism test.

    Events coalesce into time buckets of ``bucket_s`` (the evaluator
    passes an eighth of its shortest burn window): a cell per bucket,
    not per observation, so a 30-day production window holds
    O(window/bucket) cells — bounded regardless of traffic rate — and
    the sub-window scans stay proportional to buckets, not events.
    Timestamps snap UP to the bucket edge (ceil), so a snapped event is
    never older than it really is: it can only *leave* a window late
    (by < bucket_s, ≤ 1/8 of the shortest window), never get dropped
    from one it belongs to."""

    def __init__(self, keep_s: float, bucket_s: float = 0.0) -> None:
        self.keep_s = keep_s
        self.bucket_s = bucket_s
        self._cells: Deque[Tuple[float, int, int]] = deque()
        self.good_total = 0
        self.bad_total = 0

    def add(self, t: float, good: int, bad: int) -> None:
        if self.bucket_s > 0:
            t = math.ceil(t / self.bucket_s) * self.bucket_s
        cells = self._cells
        if cells and cells[-1][0] == t:
            lt, lg, lb = cells[-1]
            cells[-1] = (lt, lg + good, lb + bad)
        else:
            cells.append((t, good, bad))
        self.good_total += good
        self.bad_total += bad

    def prune(self, now: float) -> None:
        cells = self._cells
        while cells and cells[0][0] <= now - self.keep_s:
            _, g, b = cells.popleft()
            self.good_total -= g
            self.bad_total -= b

    def counts_since(self, t0: float) -> Tuple[int, int]:
        """(good, bad) of events with ``t > t0`` — newest-first walk, so
        the cost is proportional to the sub-window, not the retention."""
        good = bad = 0
        for t, g, b in reversed(self._cells):
            if t <= t0:
                break
            good += g
            bad += b
        return good, bad


class SLOEvaluator:
    """One objective's window + burn-rate + state machine. Feed events
    with ``observe``; call ``evaluate`` at any cadence — evaluation is a
    pure function of (window contents, clock), so cadence changes move
    *when* a transition is seen, never *whether*."""

    def __init__(self, spec: SLOSpec, *, clock: Callable[[], float],
                 metrics=None, label: str = "",
                 event_log: Optional[List[str]] = None,
                 on_transition=None) -> None:
        self.spec = spec.normalized()
        self.kind, _ = objective_kind(self.spec.objective)
        self.clock = clock
        self.metrics = metrics
        self.label = label or self.spec.name
        self.event_log = event_log if event_log is not None else []
        self.on_transition = on_transition
        self.state = BUDGET_OK
        # bucket at an eighth of the shortest burn window: bounded cell
        # count over a 30-day window, ≤ 12.5% timestamp skew on the one
        # window it matters most for (and far less on the longer ones)
        self._window = _EventWindow(self.spec.window_s,
                                    bucket_s=self.spec.fast_short_s / 8)
        self._last_obs_t: Optional[float] = None

    # -------------------------------------------------------------- feeding
    def observe(self, value: Optional[float] = None,
                ok: Optional[bool] = None) -> None:
        """One event. Latency objectives take ``value`` (seconds; bad
        when above target); availability takes ``ok`` directly."""
        if ok is None:
            if value is None:
                raise ValueError("observe needs value= or ok=")
            ok = value <= self.spec.target
        t = self.clock()
        self._window.add(t, int(ok), int(not ok))
        self._last_obs_t = t

    # ------------------------------------------------------------- the math
    def _burn(self, now: float, horizon_s: float) -> Optional[float]:
        """Burn rate over one window: observed bad fraction divided by
        the budget fraction (burn 1.0 = spending exactly the budget's
        sustainable rate; ``page_burn`` multiples of it page). ``None``
        on an empty window."""
        good, bad = self._window.counts_since(now - horizon_s)
        total = good + bad
        if total == 0:
            return None
        return (bad / total) / self.spec.budget_fraction

    def _pair_burn(self, now: float, short_s: float,
                   long_s: float) -> Optional[float]:
        """The multi-window rule: a pair burns at the MIN of its two
        windows (both must exceed the threshold to alert — the short
        window resets fast once the breach stops, the long window keeps
        one spike from paging). ``None`` when either window is empty."""
        short = self._burn(now, short_s)
        long_ = self._burn(now, long_s)
        if short is None or long_ is None:
            return None
        return min(short, long_)

    def _next_state(self, burn_fast: Optional[float],
                    burn_slow: Optional[float],
                    remaining: float) -> str:
        s = self.spec
        if remaining <= 0.0:
            return BUDGET_EXHAUSTED
        if self.state == BUDGET_EXHAUSTED and remaining < s.hysteresis:
            return BUDGET_EXHAUSTED        # dead band on budget refill
        lo = 1.0 - s.hysteresis
        page_on = burn_fast is not None and burn_fast >= s.page_burn
        page_hold = (self.state == BUDGET_PAGE and burn_fast is not None
                     and burn_fast >= s.page_burn * lo)
        if page_on or page_hold:
            return BUDGET_PAGE
        warn_on = (s.warn_burn > 0 and burn_slow is not None
                   and burn_slow >= s.warn_burn)
        warn_hold = (self.state in (BUDGET_WARN, BUDGET_PAGE)
                     and s.warn_burn > 0 and burn_slow is not None
                     and burn_slow >= s.warn_burn * lo)
        if warn_on or warn_hold:
            return BUDGET_WARN
        return BUDGET_OK

    # ------------------------------------------------------------ evaluation
    def evaluate(self, span=None) -> SLOStatus:
        """Compute burns + budget, run the state machine, publish gauges,
        and record any transition (event-log line, counter, span event,
        callback). ``span`` is an open `obs/trace` span transitions land
        on as ``slo.transition`` events — the autoscaler passes its tick
        span, drivers pass their root."""
        s = self.spec
        now = self.clock()
        self._window.prune(now)
        stale = (self._last_obs_t is None
                 or now - self._last_obs_t > s.stale_after_s)
        good = self._window.good_total
        bad = self._window.bad_total
        total = good + bad
        remaining = (1.0 if total == 0
                     else 1.0 - (bad / total) / s.budget_fraction)
        if stale:
            # the signal went dark: burn rates are unknowable, not
            # whatever they last were — surface staleness, hold state
            burn_fast = burn_slow = None
            state = self.state
        else:
            burn_fast = self._pair_burn(now, s.fast_short_s, s.fast_long_s)
            burn_slow = self._pair_burn(now, s.slow_short_s, s.slow_long_s)
            state = self._next_state(burn_fast, burn_slow, remaining)
        status = SLOStatus(
            name=s.name, objective=s.objective, target=s.target,
            state=state, burn_fast=burn_fast, burn_slow=burn_slow,
            budget_remaining=remaining, good=good, bad=bad, stale=stale)
        if state != self.state:
            old, self.state = self.state, state
            line = (f"t={now:.6f} slo={self.label} state={old}->{state} "
                    f"burn_fast={_fmt(burn_fast)} "
                    f"burn_slow={_fmt(burn_slow)} "
                    f"budget_remaining={remaining:.6f}")
            self.event_log.append(line)
            if span is not None:
                span.event("slo.transition", slo=self.label,
                           frm=old, to=state,
                           burn_fast=burn_fast, burn_slow=burn_slow,
                           budget_remaining=round(remaining, 6))
            if self.metrics is not None:
                self.metrics.inc("budget_transitions", label=state)
            if self.on_transition is not None:
                self.on_transition(self.label, old, state, status)
        if self.metrics is not None:
            m = self.metrics
            if burn_fast is not None:
                m.set_gauge("burn_rate_fast", burn_fast, label=self.label)
            if burn_slow is not None:
                m.set_gauge("burn_rate_slow", burn_slow, label=self.label)
            m.set_gauge("budget_remaining", remaining, label=self.label)
            m.set_gauge("budget_state", float(status.code),
                        label=self.label)
            m.set_gauge("slo_stale", float(stale), label=self.label)
        return status


def page_onsets(lines) -> List[str]:
    """The budget-log lines that BEGIN a page episode, in order: a
    transition whose ``to`` state pages (``page``/``exhausted``) while
    its ``from`` state did not. The decision ledger's
    ``slo_page:<svc>#N`` trigger ordinal indexes this list (1-based) —
    computed from the log itself on each paging onset, so a paging
    signal that resumes after a stale gap (no new transition line — the
    state machine held ``page`` through the dark window) keeps the SAME
    episode ordinal and the trigger stays resolvable."""
    out = []
    for line in lines:
        fields = dict(part.partition("=")[::2] for part in line.split(" "))
        frm, _, to = fields.get("state", "").partition("->")
        if to in (BUDGET_PAGE, BUDGET_EXHAUSTED) \
                and frm not in (BUDGET_PAGE, BUDGET_EXHAUSTED):
            out.append(line)
    return out


class SLOEngine:
    """A named set of evaluators sharing one injected clock and ONE
    event log (transitions across objectives interleave in evaluation
    order — the byte-comparable budget timeline ``make slo-soak``
    replays). Specs keep their given order; ``evaluate`` walks them in
    that order, so the log is deterministic whenever the feed is."""

    def __init__(self, specs, *, clock: Callable[[], float],
                 metrics=None, service: str = "",
                 on_transition=None) -> None:
        self.clock = clock
        self.service = service
        self.event_log: List[str] = []
        self.evaluators: Dict[str, SLOEvaluator] = {}
        for spec in specs:
            norm = spec.normalized()
            if norm.name in self.evaluators:
                raise ValueError(f"duplicate SLO name {norm.name!r}")
            label = f"{service}/{norm.name}" if service else norm.name
            self.evaluators[norm.name] = SLOEvaluator(
                norm, clock=clock, metrics=metrics, label=label,
                event_log=self.event_log, on_transition=on_transition)

    # -------------------------------------------------------------- feeding
    def observe_latency(self, kind: str, value: float) -> None:
        """One latency sample (seconds) of ``kind`` (``ttft`` / ``tpot``
        / ``queue_wait``): feeds every evaluator targeting that kind."""
        for ev in self.evaluators.values():
            if ev.kind == kind:
                ev.observe(value=value)

    def observe_outcome(self, ok: bool) -> None:
        """One request outcome: feeds every availability evaluator."""
        for ev in self.evaluators.values():
            if ev.kind == "availability":
                ev.observe(ok=ok)

    # ------------------------------------------------------------ evaluation
    def evaluate(self, span=None) -> Dict[str, SLOStatus]:
        """Evaluate every objective (spec order); returns name → status."""
        return {name: ev.evaluate(span=span)
                for name, ev in self.evaluators.items()}

    def paging(self, statuses: Optional[Dict[str, SLOStatus]] = None
               ) -> bool:
        """True when any non-stale objective is at ``page`` or worse —
        the severity hint the fleet autoscaler consumes."""
        if statuses is None:
            statuses = {n: ev.evaluate()
                        for n, ev in self.evaluators.items()}
        return any(st.state in (BUDGET_PAGE, BUDGET_EXHAUSTED)
                   and not st.stale for st in statuses.values())
