"""Goodput + cost accounting: the "what did the chips actually buy"
ledger beside the SLO engine (`obs/slo.py`).

Two accountants, one telemetry plane (`metrics.SLOMetrics` /
`metrics.TrainMetrics`):

* **``ServingAccountant``** — classifies every finished request's tokens
  as **good** (served within the latency SLO) or **degraded** (finished
  but breached, or partial output from a cancel/expiry/exhaustion), and
  counts rejected/replayed requests per tenant — the goodput ledger that
  makes "we served 1M tokens" honest about how many were worth paying
  for. Chip-seconds are attributed per tenant using the router's
  capacity weights (`serve/router.Router.set_capacity` — a mesh-sharded
  replica spans several chips, so a second of its time costs its mesh
  size): the per-tenant cost signal ROADMAP item 3's capacity broker
  prices allocations against.
* **``TrainingAccountant``** — training goodput: productive step seconds
  on NOVEL steps vs waste (replayed steps after a preemption resume,
  restart/recompile gaps, checkpoint stalls, unattributed overhead),
  surfaced as the ``TrainMetrics`` ``goodput_fraction`` gauge.
  `train/loop.py` feeds it at every host-sync window; replay detection
  is positional — a window whose global steps were already accounted is
  re-execution, which is exactly what a preemption resume from the last
  checkpoint produces.
* **``goodput_from_spans``** — the post-hoc twin: compute the same
  goodput decomposition from ``train.window`` spans in a trace dump, so
  a flight-recorder artifact answers "how much of this run was
  productive" without the live accountant.

Deterministic and stdlib-only like the rest of `obs/`: no clock reads
(time enters as arguments the callers measured), insertion/sorted
iteration, plain floats.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional

#: waste attribution buckets the training ledger recognizes. ``reshard``
#: is the live mesh-reconfiguration pause (`parallel/reshard.py` via
#: `train/loop.py`) — attributed distinctly so a live rescale's cost is
#: never misclassified as a restart or preemption, and the
#: ``goodput_fraction`` gauge prices the live path against the
#: checkpoint-restart path honestly.
WASTE_KINDS = ("replay", "restart", "recompile", "preempt", "checkpoint",
               "reshard", "overhead")


class ServingAccountant:
    """Per-tenant good/degraded token and chip-second ledger. SLO
    targets come in at construction (``ttft_slo_s`` / ``tpot_slo_s``;
    0 disables that check — a request is good when every *configured*
    target holds). ``router`` supplies chip capacities
    (``capacity_of``); explicit ``note_capacity`` calls win."""

    def __init__(self, *, ttft_slo_s: float = 0.0, tpot_slo_s: float = 0.0,
                 metrics=None, router=None) -> None:
        self.ttft_slo_s = max(float(ttft_slo_s), 0.0)
        self.tpot_slo_s = max(float(tpot_slo_s), 0.0)
        self.metrics = metrics
        self.router = router
        self._capacity: Dict[str, float] = {}
        self.good_tokens: Dict[str, int] = defaultdict(int)
        self.degraded_tokens: Dict[str, int] = defaultdict(int)
        self.rejected: Dict[str, int] = defaultdict(int)
        self.replayed: Dict[str, int] = defaultdict(int)
        self.chip_seconds: Dict[str, float] = defaultdict(float)

    # ------------------------------------------------------------- capacity
    def note_capacity(self, replica: str, chips: float) -> None:
        """Declare a replica's chip count (mirrors
        ``Router.set_capacity`` for callers without a router)."""
        if chips < 1:
            raise ValueError(f"chips must be >= 1, got {chips}")
        self._capacity[replica] = float(chips)

    def chips_of(self, replica: str) -> float:
        got = self._capacity.get(replica)
        if got is not None:
            return got
        if self.router is not None and replica:
            return float(self.router.capacity_of(replica))
        return 1.0

    # ------------------------------------------------------------ the ledger
    def within_slo(self, ttft: Optional[float],
                   tpot: Optional[float]) -> bool:
        """Every configured latency target holds. A missing sample for a
        configured target reads as a breach — "we don't know how slow it
        was" must not count as good (the no-data discipline again)."""
        if self.ttft_slo_s > 0:
            if ttft is None or ttft > self.ttft_slo_s:
                return False
        if self.tpot_slo_s > 0:
            if tpot is None or tpot > self.tpot_slo_s:
                return False
        return True

    def observe_request(self, *, tenant: str, state: str, tokens: int,
                        ttft: Optional[float] = None,
                        tpot: Optional[float] = None,
                        duration_s: float = 0.0, replica: str = "",
                        replays: int = 0) -> str:
        """Account one terminal request; returns its classification
        (``good`` / ``degraded`` / ``rejected``). ``duration_s`` is the
        request's occupancy (submit → terminal) — chip-seconds charge
        ``duration × chips(replica)`` to the tenant regardless of
        outcome: a rejected request cost nothing, a degraded one cost
        the same chips a good one did (which is the point of the
        ledger)."""
        m = self.metrics
        if replays > 0:
            self.replayed[tenant] += replays
            if m is not None:
                m.inc("replayed_requests", replays, label=tenant)
        if state == "rejected":
            self.rejected[tenant] += 1
            if m is not None:
                m.inc("rejected_requests", label=tenant)
            return "rejected"
        cost = self.chips_of(replica) * max(float(duration_s), 0.0)
        if cost > 0:
            self.chip_seconds[tenant] += cost
            if m is not None:
                m.inc("chip_seconds", cost, label=tenant)
        good = state == "done" and self.within_slo(ttft, tpot)
        if good:
            self.good_tokens[tenant] += int(tokens)
            if m is not None and tokens:
                m.inc("good_tokens", int(tokens), label=tenant)
            return "good"
        self.degraded_tokens[tenant] += int(tokens)
        if m is not None and tokens:
            m.inc("degraded_tokens", int(tokens), label=tenant)
        return "degraded"

    def summary(self) -> Dict[str, Any]:
        """Deterministic per-tenant rollup (sorted tenants) plus totals —
        the shape `tools/serve_load.py --slo` folds into its summary."""
        tenants = sorted(set(self.good_tokens) | set(self.degraded_tokens)
                         | set(self.rejected) | set(self.replayed)
                         | set(self.chip_seconds))
        per_tenant = {
            t: {
                "good_tokens": self.good_tokens.get(t, 0),
                "degraded_tokens": self.degraded_tokens.get(t, 0),
                "rejected": self.rejected.get(t, 0),
                "replayed": self.replayed.get(t, 0),
                "chip_seconds": round(self.chip_seconds.get(t, 0.0), 6),
            } for t in tenants}
        good = sum(self.good_tokens.values())
        degraded = sum(self.degraded_tokens.values())
        return {
            "good_tokens": good,
            "degraded_tokens": degraded,
            "goodput_token_fraction": (round(good / (good + degraded), 6)
                                       if good + degraded else None),
            "rejected": sum(self.rejected.values()),
            "replayed": sum(self.replayed.values()),
            "chip_seconds": round(sum(self.chip_seconds.values()), 6),
            "per_tenant": per_tenant,
        }


class TrainingAccountant:
    """Training goodput ledger. `train/loop.py` calls ``window`` at each
    host sync and ``run_complete`` when a run returns; an orchestrator
    that restarts a preempted job sets ``start_step`` to the resumed
    checkpoint step (and may add explicit ``waste`` for the
    restart/recompile gap it measured). Steps at-or-below the
    high-water mark are REPLAY — work the preemption already paid for
    once."""

    def __init__(self, *, metrics=None, start_step: int = 0) -> None:
        self.metrics = metrics
        self.start_step = int(start_step)
        self._max_step = int(start_step)
        self.productive_s = 0.0
        self.waste_s: Dict[str, float] = {k: 0.0 for k in WASTE_KINDS}
        self.preemptions = 0
        self._run_accounted = 0.0

    # ------------------------------------------------------------- the ledger
    def window(self, step: int, steps: int, step_seconds: float) -> None:
        """One host-sync window: ``steps`` loop steps ending at local
        ``step`` (global = ``start_step + step``), each costing
        ``step_seconds``. Novel steps are productive; re-executed ones
        (global end ≤ high-water mark) are replay waste."""
        end = self.start_step + int(step)
        steps = max(int(steps), 0)
        dt = max(float(step_seconds), 0.0)
        novel = max(0, min(steps, end - self._max_step))
        replay = steps - novel
        self.productive_s += novel * dt
        if replay:
            self.waste_s["replay"] += replay * dt
        self._run_accounted += steps * dt
        self._max_step = max(self._max_step, end)
        if self.metrics is not None:
            self.metrics.set_gauge("goodput_fraction",
                                   self.goodput_fraction())

    def waste(self, kind: str, seconds: float) -> None:
        """Attribute ``seconds`` of non-productive time. Unknown kinds
        fold into ``overhead`` rather than raising — the ledger must
        absorb a new caller's vocabulary, not crash it."""
        key = kind if kind in self.waste_s else "overhead"
        self.waste_s[key] += max(float(seconds), 0.0)
        if self.metrics is not None:
            self.metrics.set_gauge("goodput_fraction",
                                   self.goodput_fraction())

    def pause(self, kind: str, seconds: float) -> None:
        """An in-run measured pause (the live-reshard transform): lands
        in its waste bucket AND counts as run-accounted time, so
        ``run_complete`` does not re-classify the same seconds as
        overhead/preempt residual — the pause is attributed exactly
        once, under its own name."""
        self.waste(kind, seconds)
        self._run_accounted += max(float(seconds), 0.0)

    def run_complete(self, run_seconds: float, *,
                     preempted: bool = False) -> None:
        """Close one ``TrainLoop.run``: the gap between the run's wall
        time and its accounted step time is waste — ``preempt`` when the
        run ended on a preemption notice (drain + final save time),
        ``overhead`` otherwise (compile, sync, checkpoint cadence)."""
        residual = max(float(run_seconds) - self._run_accounted, 0.0)
        self._run_accounted = 0.0
        if preempted:
            self.preemptions += 1
        self.waste(("preempt" if preempted else "overhead"), residual)

    def resume(self, from_step: int) -> None:
        """A restarted incarnation resumes at checkpoint ``from_step``:
        subsequent windows report local steps 1.. on top of it. The
        high-water mark is NOT reset — that is how replayed steps are
        recognized."""
        self.start_step = int(from_step)

    # -------------------------------------------------------------- readouts
    def total_waste_s(self) -> float:
        return sum(self.waste_s.values())

    def goodput_fraction(self) -> float:
        total = self.productive_s + self.total_waste_s()
        if total <= 0:
            return 1.0
        return self.productive_s / total

    def summary(self) -> Dict[str, Any]:
        return {
            "productive_s": round(self.productive_s, 6),
            "waste_s": {k: round(v, 6)
                        for k, v in self.waste_s.items() if v > 0},
            "total_waste_s": round(self.total_waste_s(), 6),
            "preemptions": self.preemptions,
            "goodput_fraction": round(self.goodput_fraction(), 6),
            "steps_accounted": self._max_step,
        }


def goodput_from_spans(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Post-hoc goodput from a trace dump's ``train.window`` spans: the
    productive time is the step time the windows report
    (``steps × step_seconds`` attrs, span duration as the fallback);
    everything between the first window's start and the last window's
    end that no window covers is gap waste (compile, checkpoint drains,
    restart dead time — whatever kept the devices from stepping)."""
    windows = sorted((s for s in spans if s.get("name") == "train.window"
                      and s.get("end") is not None),
                     key=lambda s: (s["start"], s.get("span", 0)))
    if not windows:
        return {"windows": 0, "productive_s": 0.0, "span_s": 0.0,
                "gap_s": 0.0, "goodput_fraction": None}
    productive = 0.0
    covered = 0.0
    for s in windows:
        attrs = s.get("attrs") or {}
        dur = s["end"] - s["start"]
        covered += dur
        steps = attrs.get("steps")
        step_seconds = attrs.get("step_seconds")
        if steps is not None and step_seconds is not None:
            productive += float(steps) * float(step_seconds)
        else:
            productive += dur
    span_s = windows[-1]["end"] - windows[0]["start"]
    gap = max(span_s - covered, 0.0)
    total = productive + gap
    return {
        "windows": len(windows),
        "productive_s": round(productive, 6),
        "span_s": round(span_s, 6),
        "gap_s": round(gap, 6),
        "goodput_fraction": (round(productive / total, 6)
                             if total > 0 else None),
    }
