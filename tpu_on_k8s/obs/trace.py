"""Deterministic span/trace substrate: one timeline from gateway admission
to decoded token.

Every plane of the stack already records *fragments* of a request's life —
lifecycle states in `serve/lifecycle.py`, autoscaler ``decision_log``
lines, chaos event logs, Prometheus histograms — but nothing joins them
per request: a TTFT regression cannot be attributed to queue-wait vs
prefill vs handoff vs decode from any one of them. This module is the
joining substrate:

* **``Span``** — one named interval on one timeline: counter-derived ids
  (no uuids), injectable-clock timestamps (the serving plane's virtual
  clocks flow straight through), ordered attrs, and point-in-time
  ``event``s (first token, chaos injections, replays).
* **``Tracer``** — mints spans under a lock from a single monotone
  counter, collects them as they finish, and feeds an optional
  ``FlightRecorder`` (`obs/export.py`). Because ids come from a counter
  and timestamps from the injected clock, two runs of the same seeded
  trace produce **byte-identical dumps** — the property
  ``make trace-demo`` asserts and the digital-twin roadmap item
  (VirtualFlow, PAPERS.md) will replay.
* **``NOOP``** — the disabled tracer. Every instrumented call site holds
  a tracer unconditionally (``tracer or NOOP``); the noop mints one
  shared inert span, reads no clock, takes no lock, allocates nothing
  per call — tracing disabled is bit-for-bit behavior-neutral, so every
  existing determinism proof (autoscale decision logs, disagg event
  logs, chaos soaks) survives unchanged.

Span taxonomy (see `docs/observability.md` for the full catalog): a
request's root span is ``request``; its sequential phase children are
``queue`` → (``decode`` | ``prefill`` → ``handoff`` → ``decode``); the
root carries the ``first_token`` event `tools/trace_report.py` anchors
the TTFT critical path on. Control loops emit ``autoscale.tick`` /
``reconcile.inferenceservice`` spans; the train loop emits
``train.window`` spans bridged to the XLA timeline via
`utils/profiling.annotate`.

Stdlib-only: any layer may import this without dragging in jax or the
client stack (the same import discipline as `chaos/faults.py`).
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Sequence)

#: terminal statuses a span may carry; anything else is treated as a
#: domain-specific status string (e.g. a RequestState value)
STATUS_OK = "ok"
STATUS_ERROR = "error"


class Span:
    """One interval on the trace timeline. Mutate only through ``set`` /
    ``event`` / ``finish`` — the exporter reads the fields directly."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "end", "status", "attrs", "events", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, trace_id: int,
                 span_id: int, parent_id: Optional[int], start: float,
                 attrs: Dict[str, Any]) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end: Optional[float] = None
        self.status: str = STATUS_OK
        self.attrs = attrs
        self.events: List[Dict[str, Any]] = []

    # ------------------------------------------------------------- recording
    def set(self, **attrs: Any) -> "Span":
        """Attach/overwrite attributes (insertion-ordered)."""
        self.attrs.update(attrs)
        return self

    def event(self, name: str, /, at: Optional[float] = None,
              **attrs: Any) -> "Span":
        """A point-in-time marker on this span's timeline (first token,
        chaos injection, replay decision). ``at`` backdates the marker —
        the digital twin mints a request's whole span tree at its
        completion event, stamping each point from the virtual timeline
        it already computed."""
        ev: Dict[str, Any] = {"name": name,
                              "t": self._tracer.clock() if at is None
                              else at}
        if attrs:
            ev["attrs"] = attrs
        self.events.append(ev)
        return self

    def finish(self, status: str = STATUS_OK,
               at: Optional[float] = None) -> "Span":
        """End the span exactly once (idempotent — a finalize racing a
        crash sweep keeps the first verdict, mirroring
        `serve/lifecycle.finalize`)."""
        if self.end is not None:
            return self
        self.end = self._tracer.clock() if at is None else at
        self.status = status
        self._tracer._collect(self)
        return self

    # -------------------------------------------------------------- plumbing
    @property
    def finished(self) -> bool:
        return self.end is not None

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def to_dict(self) -> Dict[str, Any]:
        """The canonical export form (what ``--trace-out`` files hold and
        `tools/trace_report.py` consumes)."""
        d: Dict[str, Any] = {
            "name": self.name, "trace": self.trace_id,
            "span": self.span_id, "parent": self.parent_id,
            "start": self.start, "end": self.end, "status": self.status,
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.events:
            d["events"] = list(self.events)
        return d

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.finish(STATUS_ERROR if exc_type is not None else STATUS_OK)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Span({self.name!r} trace={self.trace_id} "
                f"span={self.span_id} status={self.status})")


class _NoopSpan:
    """The inert span the disabled tracer hands out: every method no-ops
    and returns self, so instrumented call sites never branch."""

    __slots__ = ()
    name = ""
    trace_id = 0
    span_id = 0
    parent_id = None
    start = 0.0
    end = 0.0
    status = STATUS_OK
    attrs: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    finished = True
    duration = 0.0

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self

    def event(self, name: str, /, at: Optional[float] = None,
              **attrs: Any) -> "_NoopSpan":
        return self

    def finish(self, status: str = STATUS_OK,
               at: Optional[float] = None) -> "_NoopSpan":
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {}

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


NOOP_SPAN = _NoopSpan()


class _NoopTracer:
    """Tracing disabled: no clock reads, no locks, no allocation per
    call — bit-for-bit behavior-neutral (the property every existing
    determinism proof depends on)."""

    __slots__ = ()
    enabled = False
    recorder = None

    def clock(self) -> float:
        return 0.0

    def start(self, name: str, /, parent: Any = None,
              at: Optional[float] = None, **attrs: Any) -> _NoopSpan:
        return NOOP_SPAN

    def keep(self, span: Any) -> None:
        return None

    def is_sampled(self, trace_id: int) -> bool:
        return False

    @contextlib.contextmanager
    def span(self, name: str, /, parent: Any = None, **attrs: Any
             ) -> Iterator[_NoopSpan]:
        yield NOOP_SPAN

    def crash_dump(self, reason: str) -> Optional[str]:
        return None

    def export(self) -> List[Dict[str, Any]]:
        return []

    def dump(self, path: str) -> None:
        raise RuntimeError("tracing is disabled (NOOP tracer has no spans)")


NOOP = _NoopTracer()


def ensure(tracer: Optional["Tracer"]):
    """The one idiom every instrumented constructor uses:
    ``self._tracer = ensure(tracer)`` — None means disabled."""
    return NOOP if tracer is None else tracer


class Tracer:
    """Mints and collects spans. ``clock`` is injectable (pass the same
    virtual clock the fleet runs on and the whole dump becomes a pure
    function of the seed); span/trace ids come from one monotone counter
    under the tracer lock, so id assignment is deterministic whenever the
    call sequence is (every seeded closed-loop driver is single-threaded).

    ``max_spans`` bounds retention: a long-lived server must not grow an
    unbounded span list — past the cap, finished spans still feed the
    flight recorder's ring (which is the crash artifact) but are dropped
    from the export list, and ``dropped`` counts them.

    ``sample_every`` is the head-sampling knob a million-request twin
    run needs: keep every Nth root whose name is in ``sample_names``
    (and its whole trace); shed the rest at collect time, counted by
    ``sampled_out``. ``keep(span)`` pins a trace regardless of the
    sample phase — the twin pins SLO-breaching and chaos-adjacent
    traces so every exemplar a page cites still resolves in the dump.
    Sampling decides *retention only*: ids and clock reads are
    allocated identically either way, so a sampled run's kept spans are
    byte-identical to the same spans of an unsampled run, and the
    default (``sample_every=1``) is exactly the pre-knob tracer."""

    enabled = True

    def __init__(self, clock: Callable[[], float] = time.monotonic, *,
                 recorder=None, service: str = "tpu-on-k8s",
                 max_spans: int = 200_000, sample_every: int = 1,
                 sample_names: Sequence[str] = ("request",)) -> None:
        if max_spans < 1:
            raise ValueError(f"max_spans must be >= 1, got {max_spans}")
        if sample_every < 1:
            raise ValueError(
                f"sample_every must be >= 1, got {sample_every}")
        self.clock = clock
        self.service = service
        self.recorder = recorder
        self.max_spans = max_spans
        self.sample_every = int(sample_every)
        self.sample_names = tuple(sample_names)
        self.spans: List[Span] = []       # finished spans, in finish order
        self.dropped = 0
        self.sampled_out = 0              # spans shed by the sampling knob
        self._lock = threading.Lock()
        self._next_id = 1
        self._sampled_roots = 0           # roots subject to the knob so far
        self._unsampled: set = set()      # live trace ids being shed

    # ---------------------------------------------------------------- spans
    def start(self, name: str, /, parent: Optional[Span] = None,
              at: Optional[float] = None, **attrs: Any) -> Span:
        """Begin a span. With ``parent`` the new span joins its trace;
        without, it roots a new trace whose id IS the span id (counter-
        derived — no uuid, no wall clock). ``at`` backdates the start
        (the twin mints finished timelines); id allocation and the
        sampling decision are unaffected by it."""
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            root = parent is None or not parent.trace_id
            if root and self.sample_every > 1 \
                    and name in self.sample_names:
                self._sampled_roots += 1
                if (self._sampled_roots - 1) % self.sample_every != 0:
                    self._unsampled.add(sid)
        if not root:
            tid, pid = parent.trace_id, parent.span_id
        else:
            tid, pid = sid, None
        return Span(self, name, tid, sid, pid,
                    self.clock() if at is None else at, dict(attrs))

    @contextlib.contextmanager
    def span(self, name: str, /, parent: Optional[Span] = None,
             **attrs: Any) -> Iterator[Span]:
        """``with tracer.span("autoscale.tick", svc=key) as sp: ...`` —
        finishes ``error`` if the body raises."""
        sp = self.start(name, parent, **attrs)
        try:
            yield sp
        except BaseException:
            sp.finish(STATUS_ERROR)
            raise
        sp.finish()

    def keep(self, span) -> None:
        """Pin a trace through the sampling knob: the SLO-page /
        chaos-adjacent escape hatch. Accepts a span or a trace id; must
        be called before the trace's spans finish (shed spans are gone,
        not resurrectable). No-op when the trace is already kept."""
        tid = span if isinstance(span, int) else span.trace_id
        with self._lock:
            self._unsampled.discard(tid)

    def is_sampled(self, trace_id: int) -> bool:
        """False while the sampling knob is shedding this trace — the
        gate exemplar emission sits behind, so metrics never cite a
        trace id the dump will not contain."""
        with self._lock:
            return trace_id not in self._unsampled

    def _collect(self, span: Span) -> None:
        with self._lock:
            if span.trace_id in self._unsampled:
                self.sampled_out += 1
                if span.span_id == span.trace_id:
                    # the root is the last word on its trace: once it
                    # collects, drop the shed-set entry so memory stays
                    # bounded by LIVE traces, not all traces ever shed
                    self._unsampled.discard(span.trace_id)
                return
            if len(self.spans) < self.max_spans:
                self.spans.append(span)
            else:
                self.dropped += 1
        if self.recorder is not None:
            self.recorder.record(span)

    # --------------------------------------------------------------- export
    def export(self) -> List[Dict[str, Any]]:
        """Finished spans as dicts, sorted by (trace, span) id — the
        deterministic order, independent of finish-order ties."""
        with self._lock:
            spans = list(self.spans)
        return [s.to_dict()
                for s in sorted(spans, key=lambda s: (s.trace_id,
                                                      s.span_id))]

    def dump(self, path: str) -> None:
        """Write the canonical trace file. ``sort_keys`` + fixed
        separators + no wall-clock metadata: two seeded runs produce
        byte-identical files (`make trace-demo` byte-compares them).
        A ``.gz`` path gzips deterministically (`obs/dumpio.py`) — the
        compressed bytes stay a pure function of the spans."""
        from tpu_on_k8s.obs.dumpio import open_dump
        doc = {"format": TRACE_FORMAT, "service": self.service,
               "dropped": self.dropped, "spans": self.export()}
        with open_dump(path, "w") as f:
            json.dump(doc, f, sort_keys=True, separators=(",", ":"))
            f.write("\n")

    def crash_dump(self, reason: str) -> Optional[str]:
        """Flight-recorder dump hook (engine crash, retry exhaustion):
        persists the ring of recent spans if a recorder with a directory
        is attached; returns the written path (None otherwise). Sequence
        allocation belongs to the recorder — it is the one counter all
        dump paths share, so filenames never collide."""
        if self.recorder is None:
            return None
        return self.recorder.dump(reason)


#: the trace-file format tag `tools/trace_report.py` checks
TRACE_FORMAT = "tpu-on-k8s-trace/v1"
