"""Observability: the deterministic span/trace layer joining every plane.

* `trace`  — ``Tracer`` / ``Span``: counter-derived ids, injectable-clock
  timestamps, the ``NOOP`` disabled tracer (bit-for-bit behavior-neutral);
* `export` — Chrome trace-event / Perfetto rendering + the
  ``FlightRecorder`` crash ring buffer;
* `slo`    — the SLO engine: declarative ``SLOSpec`` objectives evaluated
  over sliding windows into multi-window error-budget burn rates and
  typed ``ok/warn/page/exhausted`` budget states;
* `account` — goodput + cost accounting: per-tenant good/degraded tokens
  and chip-seconds (serving), productive-vs-waste step time (training);
* `ledger` — the decision ledger: one typed, byte-replayable provenance
  record per control-loop decision (observed signals + trace exemplars,
  SLO/chaos trigger, commit outcome, effect horizon), emitted uniformly
  by every loop riding `controller/loopkernel.LoopKernel` and joined
  into causal chains by `tools/why_report.py`.

Span producers: `serve/gateway.py`, `serve/fleet.py`, `serve/disagg.py`
(per-request lifecycle), `controller/fleetautoscaler.py` +
`controller/inferenceservice.py` (control-loop ticks), `train/loop.py`
(sync windows). Consumers: `tools/trace_report.py` (TTFT critical path),
``--trace-out`` on `tools/serve_load.py`, the flight recorder.

Stdlib-only, like `chaos/` — importable from any layer.
"""
from tpu_on_k8s.obs.account import (
    ServingAccountant,
    TrainingAccountant,
    goodput_from_spans,
)
from tpu_on_k8s.obs.export import (
    FlightRecorder,
    dump_chrome_trace,
    load_trace,
    to_chrome_trace,
)
from tpu_on_k8s.obs.ledger import (
    LEDGER_FORMAT,
    DecisionLedger,
    DecisionRecord,
    HorizonRecord,
    load_ledger,
)
from tpu_on_k8s.obs.slo import (
    BUDGET_EXHAUSTED,
    BUDGET_OK,
    BUDGET_PAGE,
    BUDGET_WARN,
    SLOEngine,
    SLOEvaluator,
    SLOSpec,
    SLOStatus,
)
from tpu_on_k8s.obs.trace import (
    NOOP,
    NOOP_SPAN,
    STATUS_ERROR,
    STATUS_OK,
    TRACE_FORMAT,
    Span,
    Tracer,
    ensure,
)

__all__ = [
    "BUDGET_EXHAUSTED",
    "BUDGET_OK",
    "BUDGET_PAGE",
    "BUDGET_WARN",
    "DecisionLedger",
    "DecisionRecord",
    "FlightRecorder",
    "HorizonRecord",
    "LEDGER_FORMAT",
    "NOOP",
    "NOOP_SPAN",
    "STATUS_ERROR",
    "STATUS_OK",
    "SLOEngine",
    "SLOEvaluator",
    "SLOSpec",
    "SLOStatus",
    "ServingAccountant",
    "Span",
    "TRACE_FORMAT",
    "Tracer",
    "TrainingAccountant",
    "dump_chrome_trace",
    "ensure",
    "goodput_from_spans",
    "load_ledger",
    "load_trace",
    "to_chrome_trace",
]
