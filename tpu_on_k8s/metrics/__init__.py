"""Job metrics (reference /root/reference/pkg/metrics/)."""

from tpu_on_k8s.metrics.metrics import JobMetrics, ServingMetrics, TrainMetrics
