"""Job lifecycle metrics.

Analog of /root/reference/pkg/metrics/metrics.go:33-124: per-kind counters
(created/deleted/success/failed/restarted), launch-delay histograms (job create →
first pod ready, job create → all pods ready), and queue-depth gauges. Backed by
prometheus_client when importable (scrapeable via ``serve()``), always mirrored in
plain dicts so tests and the local driver can read without a scrape.
"""
from __future__ import annotations

import bisect
import threading
from collections import defaultdict, deque
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

try:  # prometheus_client ships in the image; degrade gracefully anyway
    import prometheus_client as _prom
except ImportError:  # pragma: no cover
    _prom = None

# exemplar-capable Histogram.observe (prometheus_client >= 0.9): detected
# once — the exemplar rides into the client's bucket storage, so an
# OpenMetrics-negotiated scrape carries it (classic text-format scrapes
# ignore it, per the spec)
if _prom is not None:
    import inspect as _inspect
    _PROM_EXEMPLARS = "exemplar" in _inspect.signature(
        _prom.Histogram.observe).parameters
else:  # pragma: no cover
    _PROM_EXEMPLARS = False

_BUCKETS = (0.1, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300, 600)


@dataclass(frozen=True)
class _Family:
    """Declarative schema of one exported metric family — registered by
    every metrics class regardless of prometheus availability, so the
    pure-Python `render_text` fallback exports the identical families
    the prometheus backend would."""

    full: str                      # exported family name (with namespace)
    kind: str                      # "counter" | "gauge" | "histogram"
    labels: Tuple[str, ...]        # label names ((), or exactly one)
    help: str
    buckets: Optional[Tuple[float, ...]] = None


class _MetricsBase:
    """Shared mirror scaffolding: a lock, plain-dict counters/histograms
    (always readable without a scrape), and the optional prometheus
    twins populated by subclasses. The histogram mirror is a bounded
    deque — the serving plane observes per REQUEST, so an unbounded list
    would leak host RAM on a long-lived server; prometheus keeps the
    full-precision aggregates."""

    #: raw observations retained per histogram (newest win)
    MIRROR_CAP = 10_000
    #: (value, trace_id) exemplars retained per histogram
    EXEMPLAR_CAP = 64

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        cap = self.MIRROR_CAP
        self.histograms: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=cap))
        # monotone observation counts per histogram: the bounded mirror
        # rotates at cap (len() freezes), so delta readers (the
        # autoscaler's FleetScraper) position by THIS, never by len()
        self.histogram_counts: Dict[str, int] = defaultdict(int)
        # trace-id exemplars per histogram (newest win): the join key from
        # a latency observation back to its request's span tree
        # (`tpu_on_k8s/obs/trace.py`) — "which request was the p95 TTFT"
        # answered by trace id, not by guesswork
        self.exemplars: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=self.EXEMPLAR_CAP))
        # the exposition schema (mirror name -> _Family), populated by
        # the subclass via _declare whether or not prometheus imported —
        # `exposition()`'s fallback renderer walks this
        self._families: Dict[str, _Family] = {}
        # running histogram sums + per-bucket increments for the fallback
        # renderer (the bounded mirror deque rotates, so sums/buckets
        # must accrue incrementally, never be recomputed from it)
        self.histogram_sums: Dict[str, float] = defaultdict(float)
        self._bucket_counts: Dict[str, list] = {}
        self._prom_counters = {}
        self._prom_hists = {}
        self._prom_gauges = {}
        self.registry = None

    def _declare(self, name: str, full: str, kind: str, help: str,
                 labels: Tuple[str, ...] = (),
                 buckets: Optional[Tuple[float, ...]] = None) -> None:
        """Register one family: schema always, prometheus twin when the
        client imported. Subclasses call this for every exported metric,
        which is what makes ``exposition()`` backend-independent."""
        self._families[name] = _Family(full, kind, tuple(labels), help,
                                       tuple(buckets) if buckets else None)
        if kind == "histogram":
            self._bucket_counts[name] = [0] * (len(buckets or ()) + 1)
        if _prom is None or self.registry is None:
            return
        if kind == "counter":
            self._prom_counters[name] = _prom.Counter(
                full, help, list(labels), registry=self.registry)
        elif kind == "gauge":
            self._prom_gauges[name] = _prom.Gauge(
                full, help, list(labels), registry=self.registry)
        else:
            self._prom_hists[name] = _prom.Histogram(
                full, help, list(labels), buckets=buckets,
                registry=self.registry)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value
        g = self._prom_gauges.get(name)
        if g is not None:
            g.set(value)

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n
        c = self._prom_counters.get(name)
        if c is not None:
            c.inc(n)

    def observe(self, name: str, seconds: float,
                exemplar=None) -> None:
        """Record one histogram sample. ``exemplar`` (a trace id) rides
        along in a bounded mirror-side deque — the Prometheus client's
        exemplar support requires OpenMetrics negotiation, so the join
        key lives in the mirror where `tools/trace_report.py` and the
        scrape-free consumers already read."""
        with self._lock:
            self.histograms[name].append(seconds)
            self.histogram_counts[name] += 1
            self.histogram_sums[name] += seconds
            slots = self._bucket_counts.get(name)
            if slots is not None:
                fam = self._families[name]
                slots[bisect.bisect_left(fam.buckets, seconds)] += 1
            if exemplar is not None:
                self.exemplars[name].append((seconds, exemplar))
        h = self._prom_hists.get(name)
        if h is not None:
            if exemplar is not None and _PROM_EXEMPLARS:
                # attach the trace id to the client's bucket storage so
                # an OpenMetrics scrape renders it; an over-long label
                # value (the client caps exemplars at 128 runes) falls
                # back to the plain observation — the sample itself must
                # never be lost to its annotation
                try:
                    h.observe(seconds,
                              exemplar={"trace_id": str(exemplar)})
                except ValueError:
                    h.observe(seconds)
            else:
                h.observe(seconds)


class JobMetrics(_MetricsBase):
    """One instance per controller manager (kind-labelled like the reference)."""

    def __init__(self, kind: str = "TPUJob", registry=None) -> None:
        super().__init__()
        self.kind = kind
        self.gauges: Dict[Tuple[str, str], float] = {}
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s"
        for name in ("created", "deleted", "successful", "failed", "restarted"):
            self._declare(name, f"{ns}_jobs_{name}", "counter",
                          f"Jobs {name} for kind {kind}")
        self._declare("errors", f"{ns}_controller_errors_total", "counter",
                      "Exceptions caught in controller run loops")
        # optimistic-concurrency health: every retried 409 in a
        # read-modify-write loop (client update_with_retry/patch_meta).
        # A climbing rate means writers are fighting — the precursor of
        # ConflictRetriesExhausted livelocks.
        self._declare("conflict_retries", f"{ns}_conflict_retries_total",
                      "counter",
                      "Conflict (409) retries across client write loops")
        for name in ("first_pod_launch_delay_seconds",
                     "all_pods_launch_delay_seconds"):
            self._declare(name, f"{ns}_jobs_{name}", "histogram",
                          f"Job {name}", buckets=_BUCKETS)
        for name in ("running", "pending"):
            self._declare(name, f"{ns}_jobs_{name}", "gauge",
                          f"Jobs currently {name}")
        self._declare("queue_pending",
                      f"{ns}_tenant_queue_jobs_pending_count", "gauge",
                      "Pending jobs per tenant queue", labels=("queue",))

    def set_gauge(self, name: str, value: float, label: str = "") -> None:
        with self._lock:
            self.gauges[(name, label)] = value
        g = self._prom_gauges.get(name)
        if g is not None:
            (g.labels(label) if label else g).set(value)

    # convenience wrappers matching reference call sites
    def created(self) -> None:
        self.inc("created")

    def deleted(self) -> None:
        self.inc("deleted")

    def success(self) -> None:
        self.inc("successful")

    def failure(self) -> None:
        self.inc("failed")

    def restarted(self) -> None:
        self.inc("restarted")

    def error(self) -> None:
        self.inc("errors")

    def first_pod_launch_delay(self, seconds: float) -> None:
        self.observe("first_pod_launch_delay_seconds", seconds)

    def all_pods_launch_delay(self, seconds: float) -> None:
        self.observe("all_pods_launch_delay_seconds", seconds)


_SERVING_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1,
                    2.5, 5, 10, 30)


class ServingMetrics(_MetricsBase):
    """Continuous-batching serving observability (the compute plane's analog
    of ``JobMetrics`` — same prometheus + plain-dict mirror pattern, same
    ``serve()`` scrape path): request counters, time-to-first-token /
    queue-wait / request-latency histograms, slot/queue gauges. The
    reference has no serving plane; the bucket layout follows its metrics
    conventions (/root/reference/pkg/metrics/metrics.go:33-124)."""

    def __init__(self, registry=None) -> None:
        super().__init__()
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_serving"
        for name in ("requests_submitted", "requests_finished",
                     "tokens_emitted",
                     # gateway lifecycle (tpu_on_k8s/serve/gateway.py):
                     # explicit rejection, client cancel, deadline abort
                     "requests_rejected", "requests_cancelled",
                     "deadline_exceeded",
                     # per-reason rejection breakdown — an operator
                     # must be able to tell quota exhaustion from
                     # queue overflow off the scrape alone (reasons
                     # from tpu_on_k8s/serve/admission.py)
                     "rejected_queue_full", "rejected_load_shed",
                     "rejected_quota", "rejected_deadline",
                     "rejected_draining",
                     # crash recovery (tpu_on_k8s/serve/gateway.py):
                     # engine deaths, in-flight requests re-admitted
                     # through the fair queue, and requests whose
                     # replay budget ran out — together these prove
                     # no request is ever silently lost to a crash
                     "engine_crashes", "requests_replayed",
                     "retry_exhausted",
                     # streaming callbacks that raised and were detached
                     # (engine on_token/on_retire, gateway token hook):
                     # each detach warns AND counts, so a misbehaving
                     # frontend is visible on a scrape, not only in logs
                     "callback_errors"):
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Serving {name}")
        for name in ("time_to_first_token_seconds",
                     "queue_wait_seconds", "request_latency_seconds",
                     # inter-token latency (TPOT) — the streaming-felt
                     # speed, distinct from TTFT
                     "time_per_output_token_seconds"):
            self._declare(name, f"{ns}_{name}", "histogram",
                          f"Serving {name}", buckets=_SERVING_BUCKETS)
        for name in ("slots_active", "queue_depth"):
            self._declare(name, f"{ns}_{name}", "gauge", f"Serving {name}")


class SpecMetrics(_MetricsBase):
    """Speculative-decoding observability
    (`tpu_on_k8s/models/serving.py` spec rounds): proposed vs accepted
    draft tokens (their ratio IS the acceptance rate — the one number
    that decides whether speculation pays), rollbacks (a slot-round
    where the target rejected at least one proposal), draft crashes
    (the engine degraded to plain decode), and the running
    acceptance-rate gauge an operator reads off one scrape. Same
    prometheus + plain-dict mirror pattern as ``ServingMetrics``; give
    the instance to the engine's ``spec_metrics=`` and scrape it beside
    the gateway's serving metrics."""

    def __init__(self, registry=None) -> None:
        super().__init__()
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_spec"
        for name in ("spec_tokens_proposed", "spec_tokens_accepted",
                     "spec_rollbacks", "spec_draft_crashes"):
            self._declare(name, f"{ns}_{name[5:]}", "counter",
                          f"Speculative decoding {name[5:]}")
        self._declare("spec_acceptance_rate", f"{ns}_acceptance_rate",
                      "gauge", "Running draft-token acceptance rate "
                      "(accepted / proposed over the engine's lifetime)")


class PagedKVMetrics(_MetricsBase):
    """Paged-KV observability (`tpu_on_k8s/models/serving.py`
    ``kv_metrics=``): pool capacity and live-page occupancy gauges (their
    ratio is the real memory signal every control loop wants instead of
    a slot count), fresh-page allocations vs prefix-page aliases (the
    alias counter is the copy-on-write sharing actually happening),
    admission stalls (a request held in queue because the pool couldn't
    supply its reservation — the backpressure signal), and the compiled-
    program counter every LRU program-cache miss feeds (retrace pressure
    from a long tail of prompt shapes, visible before it becomes host
    RSS). Same prometheus + plain-dict mirror pattern as
    ``ServingMetrics``; give the instance to the engine's
    ``kv_metrics=`` — the programs_compiled counter works in dense mode
    too."""

    def __init__(self, registry=None) -> None:
        super().__init__()
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_paged"
        for name in ("page_allocs", "pages_aliased", "admission_stalls",
                     "programs_compiled"):
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Paged KV {name}")
        for name in ("pages_total", "pages_in_use"):
            self._declare(name, f"{ns}_{name}", "gauge",
                          f"Paged KV {name}")


class ShardMetrics(_MetricsBase):
    """Mesh-sharded serving observability (`tpu_on_k8s/models/serving.py`
    engine ``shard_metrics=`` + `serve/fleet.py` reshard rollouts): the
    per-replica mesh shape as axis-labelled gauges (one scrape answers
    "what parallelism is this replica actually running"), per-chip
    param/KV byte gauges (the model-size headroom the ``model`` axis
    buys — the number `serve_load --shard` charts shrinking), the
    export-gather byte counter (device→host gather cost of every
    KV-handoff/prefix export — what cross-mesh portability costs), and
    the reshard-rollout counter (a ``ShardingPolicy`` flip rolling the
    fleet through surge/drain/canary). Same prometheus + plain-dict
    mirror pattern as the other classes; mirror dicts key by
    ``(name, label)`` like ``AutoscaleMetrics``."""

    _AXIS_GAUGES = ("mesh_axis_size",)
    _PLAIN_GAUGES = ("param_bytes_per_chip", "kv_bytes_per_chip")
    _PLAIN_COUNTERS = ("reshard_rollouts", "export_gather_bytes")

    def __init__(self, registry=None) -> None:
        super().__init__()
        self.counters: Dict[Tuple[str, str], int] = defaultdict(int)
        self.gauges: Dict[Tuple[str, str], float] = {}
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_shard"
        for name in self._AXIS_GAUGES:
            self._declare(name, f"{ns}_{name}", "gauge", f"Shard {name}",
                          labels=("axis",))
        for name in self._PLAIN_GAUGES:
            self._declare(name, f"{ns}_{name}", "gauge", f"Shard {name}")
        for name in self._PLAIN_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter", f"Shard {name}")

    def inc(self, name: str, n: int = 1, label: str = "") -> None:
        with self._lock:
            self.counters[(name, label)] += n
        c = self._prom_counters.get(name)
        if c is not None:
            c.inc(n)

    def set_gauge(self, name: str, value: float, label: str = "") -> None:
        with self._lock:
            self.gauges[(name, label)] = value
        g = self._prom_gauges.get(name)
        if g is not None:
            (g.labels(label) if name in self._AXIS_GAUGES else g).set(value)

    #: the serving mesh's standard axes — every ``set_mesh_axes`` write
    #: covers at least these, so a reshard that DROPS an axis overwrites
    #: its old gauge (absent = 1) instead of leaving it stale
    MESH_AXES = ("data", "model", "expert")

    def set_mesh_axes(self, mesh_axes) -> None:
        """Publish a replica's mesh shape: every standard axis written
        (absent = 1) plus any extra non-trivial axes. The ONE writer
        both the engine and the fleet call — last caller wins by
        design (a fleet converges to one shape; the definitive
        per-replica view is ``engine.shard_report()``)."""
        axes = {a: 1 for a in self.MESH_AXES}
        axes.update(mesh_axes or {})
        for axis, size in sorted(axes.items()):
            self.set_gauge("mesh_axis_size", size, label=axis)


class TrainMetrics(_MetricsBase):
    """Training-loop observability, fed by `tpu_on_k8s/train/loop.py`'s
    ``TrainLoop`` at every host-sync window (same prometheus + plain-dict
    mirror pattern and ``serve()`` scrape path as the job/serving metrics):
    step-time / tokens-per-sec / MFU gauges (MFU's denominator comes from
    ``compiled.cost_analysis()`` via ``train/compile.py``, not the 6·N·T
    estimate), host-sync and async-checkpoint counters, and the watchdog's
    stalled-step counter — a hung collective becomes a scrapeable signal."""

    def __init__(self, registry=None) -> None:
        super().__init__()
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_train"
        for name in ("host_syncs", "checkpoints_enqueued",
                     "checkpoint_failures", "stalled_steps",
                     # profiling hooks that failed and degraded to
                     # warnings (server bind, trace start/finalize) —
                     # best-effort, but never silent
                     "profiling_failures"):
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Training loop {name}")
        for name in ("step_seconds", "tokens_per_sec", "mfu",
                     "steps_inflight",
                     # goodput: productive (novel) step seconds over
                     # productive + waste (replayed steps, restart/
                     # recompile gaps, preemption drains) — fed by the
                     # TrainingAccountant (`tpu_on_k8s/obs/account.py`)
                     # the TrainLoop carries
                     "goodput_fraction"):
            self._declare(name, f"{ns}_{name}", "gauge",
                          f"Training loop {name}")


class ReshardMetrics(_MetricsBase):
    """Live mesh-reconfiguration observability
    (`tpu_on_k8s/parallel/reshard.py` transforms driven through
    `train/loop.py`): how many live reshards ran, how many fell back to
    the checkpoint-restart path (the fallback counter is the health
    signal — a climbing rate means live rescale is not paying), the
    bytes the transfer plans actually moved (leaves whose layout
    changed; unmoved leaves cost nothing), and the last transform's
    pause seconds — the number the goodput ledger's ``reshard`` bucket
    accumulates and `tools/reshard_soak.py` races against the
    checkpoint-restart arm. Same prometheus + plain-dict mirror pattern
    as the other classes."""

    def __init__(self, registry=None) -> None:
        super().__init__()
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_reshard"
        for name, help in (("reshards", "Live mesh reshards applied"),
                           ("reshard_fallbacks",
                            "Live reshards aborted and fallen back to "
                            "checkpoint-restart"),
                           ("reshard_ack_failures",
                            "Reshard ack callbacks that raised (the "
                            "transform outcome stands; the control-plane "
                            "write did not land)"),
                           ("bytes_moved",
                            "Bytes moved by reshard transfer plans")):
            self._declare(name, f"{ns}_{name}", "counter", help)
        self._declare("transform_seconds", f"{ns}_transform_seconds",
                      "gauge", "Last reshard transform pause in seconds")


class FleetMetrics(_MetricsBase):
    """Serving-fleet observability (`tpu_on_k8s/serve/fleet.py`): the
    router/rollout layer above per-replica ``ServingMetrics``. Counters
    and gauges carry a ``replica`` label so one scrape shows the whole
    fleet's balance (in-flight per replica, routed/rerouted per replica)
    next to the fleet-wide rollout state — the per-replica breakdown an
    operator needs to see a hot replica or a stuck drain. Mirror dicts
    key by ``(name, replica)`` like ``JobMetrics`` keys by label."""

    #: rollout phase gauge encoding (stable — lands in dashboards)
    ROLLOUT_PHASE_CODES = {"idle": 0, "surging": 1, "shifting": 2,
                           "draining": 3, "complete": 4}

    _LABELED_COUNTERS = ("requests_routed", "requests_rerouted",
                         "requests_rebalanced")
    _PLAIN_COUNTERS = ("replicas_ejected", "prefix_cache_hits",
                       "prefix_cache_misses", "rollout_interrupts",
                       "rollouts_completed", "readiness_flaps",
                       "scale_ups", "scale_downs",
                       # disaggregated serving (tpu_on_k8s/serve/disagg.py):
                       # the prefill→decode KV handoff link — lost/corrupt
                       # are the chaos-injected failures whose replays the
                       # zero-silent-loss proof counts
                       "handoffs_enqueued", "handoffs_adopted",
                       "handoffs_lost", "handoffs_corrupt",
                       "requests_replayed",
                       # fleet prefix/KV store (tpu_on_k8s/serve/kvstore.py):
                       # misses ARE the fleet-wide prefix-prefill recompute
                       # count the disagg acceptance test compares
                       "prefix_store_hits", "prefix_store_misses",
                       "prefix_store_promotes", "prefix_store_evictions",
                       "prefix_store_demotes",
                       # streaming callbacks that raised and were
                       # detached (disagg token hook) — warned AND
                       # counted, mirroring ServingMetrics
                       "callback_errors")
    _LABELED_GAUGES = ("in_flight", "queue_depth", "outstanding_tokens")
    _PLAIN_GAUGES = ("replicas_ready", "replicas_total", "rollout_phase",
                     "handoff_queue_depth", "prefix_store_overflow_bytes")
    #: per-pool view of a disaggregated fleet (label value: "prefill" /
    #: "decode") — one scrape shows both pools' load side by side, which
    #: is exactly what the per-pool autoscaler loops act on
    _POOL_GAUGES = ("pool_replicas_ready", "pool_queue_depth",
                    "pool_inflight_tokens", "pool_slots")

    def __init__(self, registry=None) -> None:
        super().__init__()
        self.counters: Dict[Tuple[str, str], int] = defaultdict(int)
        self.gauges: Dict[Tuple[str, str], float] = {}
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_fleet"
        for name in self._LABELED_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter", f"Fleet {name}",
                          labels=("replica",))
        for name in self._PLAIN_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter", f"Fleet {name}")
        for name in self._LABELED_GAUGES:
            self._declare(name, f"{ns}_{name}", "gauge", f"Fleet {name}",
                          labels=("replica",))
        for name in self._PLAIN_GAUGES:
            self._declare(name, f"{ns}_{name}", "gauge", f"Fleet {name}")
        for name in self._POOL_GAUGES:
            self._declare(name, f"{ns}_{name}", "gauge", f"Fleet {name}",
                          labels=("pool",))
        # handoff queue wait: enqueue → adoption on a decode replica
        # (the latency the handoff link adds to TTFT)
        self._declare("handoff_wait_seconds", f"{ns}_handoff_wait_seconds",
                      "histogram", "Fleet handoff_wait_seconds",
                      buckets=_SERVING_BUCKETS)

    def inc(self, name: str, n: int = 1, replica: str = "") -> None:
        with self._lock:
            self.counters[(name, replica)] += n
        c = self._prom_counters.get(name)
        if c is not None:
            (c.labels(replica) if name in self._LABELED_COUNTERS
             else c).inc(n)

    def set_gauge(self, name: str, value: float, replica: str = "",
                  pool: str = "") -> None:
        label = pool or replica
        with self._lock:
            self.gauges[(name, label)] = value
        g = self._prom_gauges.get(name)
        if g is not None:
            if name in self._LABELED_GAUGES:
                g.labels(replica).set(value)
            elif name in self._POOL_GAUGES:
                g.labels(pool).set(value)
            else:
                g.set(value)

    def set_rollout_phase(self, phase: str) -> None:
        self.set_gauge("rollout_phase",
                       self.ROLLOUT_PHASE_CODES.get(phase, -1))


class AutoscaleMetrics(_MetricsBase):
    """Serving-autoscaler observability (`controller/fleetautoscaler.py`
    + `tpu_on_k8s/autoscale/`): every decision (labelled by action, so a
    thrashing loop is visible as alternating up/down increments), patch
    failures, stale scrapes, and per-service gauges for the closed
    loop's input (observed TTFT/queue-wait p95, queue depth, tokens per
    slot) next to its output (``desired_replicas``) — an operator can
    read SLO breach → decision → target off one scrape. Mirror dicts
    key by ``(name, label)`` like ``JobMetrics``."""

    _ACTION_COUNTERS = ("decisions",)
    _PLAIN_COUNTERS = ("patch_failures", "stale_scrapes", "ticks",
                       "tick_errors", "broker_harvests", "broker_degrades")
    _SERVICE_GAUGES = ("desired_replicas", "current_replicas",
                       "observed_ttft_p95", "observed_queue_wait_p95",
                       "observed_tpot_p95",
                       "observed_queue_depth", "observed_tokens_per_slot",
                       "signal_stale")

    def __init__(self, registry=None) -> None:
        super().__init__()
        self.counters: Dict[Tuple[str, str], int] = defaultdict(int)
        self.gauges: Dict[Tuple[str, str], float] = {}
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_autoscale"
        for name in self._ACTION_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Autoscale {name}", labels=("action",))
        for name in self._PLAIN_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Autoscale {name}")
        for name in self._SERVICE_GAUGES:
            self._declare(name, f"{ns}_{name}", "gauge",
                          f"Autoscale {name}", labels=("service",))

    def inc(self, name: str, n: int = 1, label: str = "") -> None:
        with self._lock:
            self.counters[(name, label)] += n
        c = self._prom_counters.get(name)
        if c is not None:
            (c.labels(label) if name in self._ACTION_COUNTERS else c).inc(n)

    def set_gauge(self, name: str, value: float, label: str = "") -> None:
        with self._lock:
            self.gauges[(name, label)] = value
        g = self._prom_gauges.get(name)
        if g is not None:
            (g.labels(label) if name in self._SERVICE_GAUGES else g).set(
                value)

    def decision(self, action: str) -> None:
        self.inc("decisions", label=action)


class SLOMetrics(_MetricsBase):
    """The SLO/error-budget telemetry plane (`tpu_on_k8s/obs/slo.py`
    engine + `obs/account.py` accountants): per-objective multi-window
    burn-rate gauges (fast pair pages, slow pair warns), the remaining
    error-budget fraction, the encoded budget state, and the staleness
    bit — plus the goodput/cost ledger: per-tenant good vs degraded
    tokens (served within SLO or not), rejected/replayed requests, and
    chip-seconds attributed through router capacity weights. Same
    prometheus + plain-dict mirror pattern as the other classes; mirror
    dicts key by ``(name, label)`` like ``AutoscaleMetrics``."""

    #: budget-state gauge encoding (stable — lands in dashboards);
    #: mirrors `obs/slo.BUDGET_STATE_CODES`
    BUDGET_STATE_CODES = {"ok": 0, "warn": 1, "page": 2, "exhausted": 3}

    _SLO_GAUGES = ("burn_rate_fast", "burn_rate_slow", "budget_remaining",
                   "budget_state", "slo_stale")
    _STATE_COUNTERS = ("budget_transitions",)
    _TENANT_COUNTERS = ("good_tokens", "degraded_tokens",
                        "rejected_requests", "replayed_requests",
                        "chip_seconds")

    def __init__(self, registry=None) -> None:
        super().__init__()
        self.counters: Dict[Tuple[str, str], int] = defaultdict(int)
        self.gauges: Dict[Tuple[str, str], float] = {}
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_slo"
        for name in self._SLO_GAUGES:
            self._declare(name, f"{ns}_{name}", "gauge", f"SLO {name}",
                          labels=("slo",))
        for name in self._STATE_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter", f"SLO {name}",
                          labels=("state",))
        for name in self._TENANT_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter", f"SLO {name}",
                          labels=("tenant",))

    def inc(self, name: str, n=1, label: str = "") -> None:
        with self._lock:
            self.counters[(name, label)] += n
        c = self._prom_counters.get(name)
        if c is not None:
            c.labels(label).inc(n)

    def set_gauge(self, name: str, value: float, label: str = "") -> None:
        with self._lock:
            self.gauges[(name, label)] = value
        g = self._prom_gauges.get(name)
        if g is not None:
            g.labels(label).set(value)


class LedgerMetrics(_MetricsBase):
    """Decision-ledger telemetry (`tpu_on_k8s/obs/ledger.py`, fed by
    every control loop riding `controller/loopkernel.LoopKernel`): the
    per-loop decision counter labelled ``<loop>|<outcome>`` (outcome
    class: ``landed`` / ``conflict`` / ``fallback`` / ``hold`` /
    ``skip`` — one combined label because the mirror/fallback
    exposition schema carries at most one label per family, and the
    loop×outcome product is what an operator actually filters on),
    commit failures (patches that never landed — the loop retries at
    full speed, but a climbing rate means writers are fighting), and
    the ``open_effect_horizons`` gauge — committed decisions whose
    effect (replicas ready, rollout complete, burn recovered) has not
    yet been observed; a climbing gauge means the loops are committing
    changes whose effects never land. Same prometheus + plain-dict
    mirror pattern as the other classes; mirror dicts key by
    ``(name, label)`` like ``AutoscaleMetrics``."""

    _LOOP_COUNTERS = ("decisions",)
    _PLAIN_COUNTERS = ("commit_failures",)

    def __init__(self, registry=None) -> None:
        super().__init__()
        self.counters: Dict[Tuple[str, str], int] = defaultdict(int)
        self.gauges: Dict[Tuple[str, str], float] = {}
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_ledger"
        for name in self._LOOP_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Ledger {name}", labels=("loop_outcome",))
        for name in self._PLAIN_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Ledger {name}")
        self._declare("open_effect_horizons", f"{ns}_open_effect_horizons",
                      "gauge", "Committed decisions whose effect horizon "
                      "is still open")

    def inc(self, name: str, n: int = 1, label: str = "") -> None:
        with self._lock:
            self.counters[(name, label)] += n
        c = self._prom_counters.get(name)
        if c is not None:
            (c.labels(label) if name in self._LOOP_COUNTERS else c).inc(n)

    def set_gauge(self, name: str, value: float, label: str = "") -> None:
        with self._lock:
            self.gauges[(name, label)] = value
        g = self._prom_gauges.get(name)
        if g is not None:
            g.set(value)


class BrokerMetrics(_MetricsBase):
    """Capacity-market telemetry (`tpu_on_k8s/coordinator/broker.py`):
    clearing counters — grants admitted through the ``request_capacity``
    gate, refusals (pressure opened), degrades (rung 1), harvests /
    preempts (rungs 2–3), final typed refusals (rung 4), managed-lane
    fills, expired grants, lane commit conflicts, and crashed clearing
    ticks — next to the
    market gauges: free chips after clearing, lanes under pressure, and
    the configured capacity. One label-free family each: the market is
    one per operator, and per-lane attribution already lives in the
    decision ledger's ``broker/<lane>`` loops. Mirror dicts key by
    ``(name, label)`` like ``AutoscaleMetrics``."""

    _PLAIN_COUNTERS = ("grants", "refusals", "degrades", "harvests",
                       "preempts", "refuse_final", "fills",
                       "grant_expired", "lane_conflicts", "tick_errors")
    _MARKET_GAUGES = ("free_chips", "pressure_lanes", "capacity_chips")

    def __init__(self, registry=None) -> None:
        super().__init__()
        self.counters: Dict[Tuple[str, str], int] = defaultdict(int)
        self.gauges: Dict[Tuple[str, str], float] = {}
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_broker"
        for name in self._PLAIN_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Broker {name}")
        for name in self._MARKET_GAUGES:
            self._declare(name, f"{ns}_{name}", "gauge",
                          f"Broker {name}")

    def inc(self, name: str, n: int = 1, label: str = "") -> None:
        with self._lock:
            self.counters[(name, label)] += n
        c = self._prom_counters.get(name)
        if c is not None:
            c.inc(n)

    def set_gauge(self, name: str, value: float, label: str = "") -> None:
        with self._lock:
            self.gauges[(name, label)] = value
        g = self._prom_gauges.get(name)
        if g is not None:
            g.set(value)


class SimMetrics(_MetricsBase):
    """Digital-twin observability (`tpu_on_k8s/sim/twin.py`): how much
    virtual time the event loop covered, how many events and requests it
    processed, and — when the driver injects a wall clock
    (`tools/twin_soak.py` passes ``time.perf_counter``; the twin itself
    never reads wall time, per the determinism gate) — the wall seconds
    spent and the ``speedup`` gauge (virtual/wall), the >1000x headline
    the twin-soak acceptance asserts. Same prometheus + plain-dict
    mirror pattern as the other classes."""

    def __init__(self, registry=None) -> None:
        super().__init__()
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_sim"
        for name in ("events_processed", "requests_simulated"):
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Digital twin {name}")
        for name in ("virtual_seconds_simulated", "wall_seconds",
                     "speedup"):
            self._declare(name, f"{ns}_{name}", "gauge",
                          f"Digital twin {name}")


class FuzzMetrics(_MetricsBase):
    """Scenario-fuzz campaign telemetry (`tpu_on_k8s/sim/fuzz/`):
    twin evaluations spent (exploration + shrink combined count here;
    ``shrink_evals`` separates the minimization share), failures the
    oracle confirmed, failures de-duplicated away as repeats of an
    already-recorded (base, kind-set) signature, and corpus entries
    emitted. All counters: a campaign is a batch run, the interesting
    rates are per-campaign deltas, and the driver prints the totals."""

    def __init__(self, registry=None) -> None:
        super().__init__()
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_fuzz"
        for name in ("evals", "failures_found", "dedup_skipped",
                     "shrink_evals", "corpus_entries"):
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Scenario fuzzer {name}")


class ModelPoolMetrics(_MetricsBase):
    """Multi-model density telemetry (`tpu_on_k8s/serve/modelpool.py`):
    the hot-swap plane one replica gang runs when it hosts several
    ModelVersion serving trees. Counters: swaps applied (a params-tree
    replace, no recompile), swap failures (the replace died mid-flight —
    previous params stayed live; a climbing rate means the artifact
    store or staging path is sick), swap retries, residency evictions
    (a model pushed out of the LRU set — its prefix pages flushed
    surgically), and the per-model token/request counters (labelled by
    model, the tenant-accounting join key). The ``swap_seconds``
    histogram is the measured swap-in latency — the cold-start signal
    the FleetAutoscaler reads beside TTFT. Gauges: models resident
    (prefixes warm on device) and queued requests across the per-model
    lanes. Same prometheus + plain-dict mirror pattern as the other
    classes; mirror dicts key by ``(name, label)`` like
    ``AutoscaleMetrics``."""

    _MODEL_COUNTERS = ("model_tokens", "model_requests")
    _PLAIN_COUNTERS = ("swaps", "swap_failures", "swap_retries",
                       "evictions", "prefix_flushes")
    _PLAIN_GAUGES = ("resident_models", "queued_requests")

    def __init__(self, registry=None) -> None:
        super().__init__()
        self.counters: Dict[Tuple[str, str], int] = defaultdict(int)
        self.gauges: Dict[Tuple[str, str], float] = {}
        if _prom is not None:
            self.registry = registry or _prom.CollectorRegistry()
        ns = "tpu_on_k8s_modelpool"
        for name in self._MODEL_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Model pool {name}", labels=("model",))
        for name in self._PLAIN_COUNTERS:
            self._declare(name, f"{ns}_{name}", "counter",
                          f"Model pool {name}")
        for name in self._PLAIN_GAUGES:
            self._declare(name, f"{ns}_{name}", "gauge",
                          f"Model pool {name}")
        self._declare("swap_seconds", f"{ns}_swap_seconds", "histogram",
                      "Model pool swap_seconds (swap-in latency: the "
                      "cold-start signal beside TTFT)",
                      buckets=_SERVING_BUCKETS)

    def inc(self, name: str, n: int = 1, label: str = "") -> None:
        with self._lock:
            self.counters[(name, label)] += n
        c = self._prom_counters.get(name)
        if c is not None:
            (c.labels(label) if name in self._MODEL_COUNTERS else c).inc(n)

    def set_gauge(self, name: str, value: float, label: str = "") -> None:
        with self._lock:
            self.gauges[(name, label)] = value
        g = self._prom_gauges.get(name)
        if g is not None:
            g.set(value)


def count_detached_callback(metrics, message: str) -> None:
    """The count-and-warn tail shared by every streaming-callback
    isolation site (engine ``on_token``/``on_retire``, gateway and
    disagg token hooks): the CALLER has already detached the raising
    callback — which attribute to clear is site-specific — and this
    records it on the ``callback_errors`` counter (when a metrics sink
    is attached) plus a warning carrying the site's message."""
    if metrics is not None:
        metrics.inc("callback_errors")
    import warnings
    warnings.warn(message, stacklevel=3)


def _escape_label(v: str) -> str:
    """Label-value escaping per the exposition format: backslash first
    (escaping the escapes), then double-quote, then newline."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    """HELP-text escaping: backslash and newline (quotes are legal)."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Sample-value rendering matching prometheus_client's float style
    (integers carry a trailing ``.0``)."""
    return repr(float(v))


def _mirror_entries(mirror: dict, name: str):
    """All (label_value, value) pairs of family ``name`` in a mirror dict
    whose keys are either plain names or ``(name, label)`` tuples —
    sorted by label for deterministic output."""
    out = []
    for key, val in mirror.items():
        mname, label = key if isinstance(key, tuple) else (key, "")
        if mname == name:
            out.append((label, val))
    return sorted(out, key=lambda kv: str(kv[0]))


def _bucket_exemplars(fam: _Family, exemplars) -> dict:
    """Bucket index → newest retained ``(value, trace_id)`` exemplar
    whose value falls inside that bucket's ``(prev, bound]`` range (the
    OpenMetrics rule: a bucket's exemplar must lie within it). Index
    ``len(buckets)`` is the ``+Inf`` bucket."""
    out: dict = {}
    bounds = fam.buckets or ()
    for value, trace_id in exemplars:     # oldest → newest: newest wins
        out[bisect.bisect_left(bounds, value)] = (value, trace_id)
    return out


def _exemplar_suffix(ex) -> str:
    """The OpenMetrics exemplar clause appended to a bucket sample:
    ``# {trace_id="..."} value`` (no timestamp — the retained exemplars
    are value+trace-id pairs, and a wall stamp would break the
    byte-identical-exposition property deterministic runs rely on)."""
    if ex is None:
        return ""
    value, trace_id = ex
    return f' # {{trace_id="{_escape_label(str(trace_id))}"}} {_fmt(value)}'


def render_text(metrics, *, openmetrics: bool = False) -> str:
    """Pure-Python Prometheus text-format renderer over the mirror dicts
    + declared family schema — what ``exposition()`` falls back to when
    prometheus_client is absent, so a scrape body exists on any image.
    Conformant: counter families carry the ``_total`` suffix, histograms
    render cumulative ``le`` buckets / ``_sum`` / ``_count``, and label
    values escape backslash, double-quote, and newline.

    ``openmetrics=True`` renders the OpenMetrics dialect instead:
    counter ``# TYPE`` lines use the bare family name (samples keep the
    ``_total`` suffix), the body ends with ``# EOF``, and histogram
    bucket samples carry the retained ``(value, trace_id)`` exemplars —
    the mirror-side deque `observe()` fills is exposition-visible, not a
    private side channel (exemplars are an OpenMetrics-only construct;
    the classic format has no legal syntax for them)."""
    with metrics._lock:
        counters = dict(metrics.counters)
        gauges = dict(metrics.gauges)
        hist_counts = dict(metrics.histogram_counts)
        hist_sums = dict(metrics.histogram_sums)
        bucket_counts = {k: list(v)
                         for k, v in metrics._bucket_counts.items()}
        exemplars = {k: list(v) for k, v in metrics.exemplars.items()}
    lines = []

    def sample(fname: str, fam: _Family, label, value) -> None:
        lbl = ""
        if fam.labels and label is not None:
            lbl = f'{{{fam.labels[0]}="{_escape_label(str(label))}"}}'
        lines.append(f"{fname}{lbl} {_fmt(value)}")

    for name, fam in metrics._families.items():
        if fam.kind == "counter":
            fname = (fam.full if fam.full.endswith("_total")
                     else fam.full + "_total")
            # OpenMetrics declares the FAMILY (no _total); samples keep it
            tname = fname[:-len("_total")] if openmetrics else fname
            lines.append(f"# HELP {tname} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {tname} counter")
            entries = _mirror_entries(counters, name)
            if not entries and not fam.labels:
                entries = [("", 0)]       # prom exports unlabeled at 0
            for label, val in entries:
                sample(fname, fam, label if fam.labels else None, val)
        elif fam.kind == "gauge":
            lines.append(f"# HELP {fam.full} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {fam.full} gauge")
            entries = _mirror_entries(gauges, name)
            if not entries and not fam.labels:
                entries = [("", 0.0)]
            for label, val in entries:
                sample(fam.full, fam, label if fam.labels else None, val)
        else:
            lines.append(f"# HELP {fam.full} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {fam.full} histogram")
            slots = bucket_counts.get(name, [0])
            by_bucket = (_bucket_exemplars(fam, exemplars.get(name, ()))
                         if openmetrics else {})
            cum = 0
            for i, (bound, n) in enumerate(zip(fam.buckets or (), slots)):
                cum += n
                lines.append(f'{fam.full}_bucket{{le="{_fmt(bound)}"}} '
                             f"{_fmt(cum)}"
                             f"{_exemplar_suffix(by_bucket.get(i))}")
            cum += slots[-1]
            lines.append(f'{fam.full}_bucket{{le="+Inf"}} {_fmt(cum)}'
                         f"{_exemplar_suffix(by_bucket.get(len(fam.buckets or ())))}")
            lines.append(f"{fam.full}_count "
                         f"{_fmt(hist_counts.get(name, 0))}")
            lines.append(f"{fam.full}_sum "
                         f"{_fmt(hist_sums.get(name, 0.0))}")
    if openmetrics:
        lines.append("# EOF")
    return "\n".join(lines) + "\n"


def exposition(metrics, *, openmetrics: bool = False) -> str:
    """The Prometheus text-format scrape body for any metrics instance
    (what ``serve()``'s endpoint returns) — separated out so tests and
    push-style exporters can render without binding a port. With
    prometheus_client importable this is its canonical rendering; without
    it, the `render_text` fallback over the mirrors + declared schema —
    never a RuntimeError, an image without the client still scrapes.

    ``openmetrics=True`` is the exemplar-carrying dialect (what a scrape
    negotiating ``application/openmetrics-text`` gets): the prometheus
    backend renders through the client's OpenMetrics exposition (the
    exemplars `observe()` attached ride its bucket storage), the
    fallback through ``render_text(openmetrics=True)`` over the
    mirror-side exemplar deques — BOTH backends surface the retained
    ``(value, trace_id)`` pairs on histogram buckets."""
    if _prom is not None and metrics.registry is not None:
        if openmetrics:
            from prometheus_client.openmetrics import (
                exposition as _om_exposition,
            )
            return _om_exposition.generate_latest(
                metrics.registry).decode()
        return _prom.generate_latest(metrics.registry).decode()
    return render_text(metrics, openmetrics=openmetrics)


def serve(metrics, port: int = 8443):  # pragma: no cover - live mode
    """Expose /metrics (reference pkg/metrics/server.go:29-37) for a
    ``JobMetrics``, ``ServingMetrics``, or ``FleetMetrics`` instance
    (the scrape body is ``exposition(metrics)``)."""
    if _prom is None or metrics.registry is None:
        raise RuntimeError("prometheus_client unavailable")
    return _prom.start_http_server(port, registry=metrics.registry)
