"""Job lifecycle metrics.

Analog of /root/reference/pkg/metrics/metrics.go:33-124: per-kind counters
(created/deleted/success/failed/restarted), launch-delay histograms (job create →
first pod ready, job create → all pods ready), and queue-depth gauges. Backed by
prometheus_client when importable (scrapeable via ``serve()``), always mirrored in
plain dicts so tests and the local driver can read without a scrape.
"""
from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

try:  # prometheus_client ships in the image; degrade gracefully anyway
    import prometheus_client as _prom
except ImportError:  # pragma: no cover
    _prom = None

_BUCKETS = (0.1, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300, 600)


class JobMetrics:
    """One instance per controller manager (kind-labelled like the reference)."""

    def __init__(self, kind: str = "TPUJob", registry=None) -> None:
        self.kind = kind
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = defaultdict(int)
        self.histograms: Dict[str, List[float]] = defaultdict(list)
        self.gauges: Dict[Tuple[str, str], float] = {}
        self._prom_counters = {}
        self._prom_hists = {}
        self._prom_gauges = {}
        if _prom is not None:
            registry = registry or _prom.CollectorRegistry()
            self.registry = registry
            ns = "tpu_on_k8s"
            for name in ("created", "deleted", "successful", "failed", "restarted"):
                self._prom_counters[name] = _prom.Counter(
                    f"{ns}_jobs_{name}", f"Jobs {name} for kind {kind}",
                    registry=registry)
            self._prom_counters["errors"] = _prom.Counter(
                f"{ns}_controller_errors_total",
                "Exceptions caught in controller run loops", registry=registry)
            for name in ("first_pod_launch_delay_seconds", "all_pods_launch_delay_seconds"):
                self._prom_hists[name] = _prom.Histogram(
                    f"{ns}_jobs_{name}", f"Job {name}", buckets=_BUCKETS,
                    registry=registry)
            for name in ("running", "pending"):
                self._prom_gauges[name] = _prom.Gauge(
                    f"{ns}_jobs_{name}", f"Jobs currently {name}", registry=registry)
            self._prom_gauges["queue_pending"] = _prom.Gauge(
                f"{ns}_tenant_queue_jobs_pending_count", "Pending jobs per tenant queue",
                ["queue"], registry=registry)
        else:  # pragma: no cover
            self.registry = None

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] += n
        c = self._prom_counters.get(name)
        if c is not None:
            c.inc(n)

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            self.histograms[name].append(seconds)
        h = self._prom_hists.get(name)
        if h is not None:
            h.observe(seconds)

    def set_gauge(self, name: str, value: float, label: str = "") -> None:
        with self._lock:
            self.gauges[(name, label)] = value
        g = self._prom_gauges.get(name)
        if g is not None:
            (g.labels(label) if label else g).set(value)

    # convenience wrappers matching reference call sites
    def created(self) -> None:
        self.inc("created")

    def deleted(self) -> None:
        self.inc("deleted")

    def success(self) -> None:
        self.inc("successful")

    def failure(self) -> None:
        self.inc("failed")

    def restarted(self) -> None:
        self.inc("restarted")

    def error(self) -> None:
        self.inc("errors")

    def first_pod_launch_delay(self, seconds: float) -> None:
        self.observe("first_pod_launch_delay_seconds", seconds)

    def all_pods_launch_delay(self, seconds: float) -> None:
        self.observe("all_pods_launch_delay_seconds", seconds)


def serve(metrics: JobMetrics, port: int = 8443):  # pragma: no cover - live mode
    """Expose /metrics (reference pkg/metrics/server.go:29-37)."""
    if _prom is None or metrics.registry is None:
        raise RuntimeError("prometheus_client unavailable")
    return _prom.start_http_server(port, registry=metrics.registry)
